"""Fused batched k-NN as a Pallas TPU kernel.

The XLA path (ops/knn.py) materializes the ``(M, N, N)`` pairwise-distance
tensor in HBM and runs ``jax.lax.top_k`` over it — at the BASELINE.json
config-4 scale (M=4096 formations x N=100 agents, every step) that is
~160 MB of HBM round-trip per rollout step plus a sort-based top-k XLA
can't fuse through. This kernel keeps the whole per-formation problem in
VMEM: distance matrix, iterative k-extraction (k unrolled argmin passes —
the standard small-k trick; each pass is one VPU reduction over lanes),
and the neighbor gather via one-hot select, with only the ``(M, k, N)``
results ever touching HBM.

Layout notes (guide: /opt/skills/guides/pallas_guide.md):
- positions are fed struct-of-arrays (x and y as separate ``(M, 1, N)``
  planes) so the lane dimension is the agent axis padded to 128, instead
  of a 2-wide trailing dimension padded 64x; the singleton middle axis
  keeps every block Mosaic-legal at any ``block_m`` (see ``_pad_planes``);
- outputs are ``(M, k, N)`` (k on the sublane axis) and transposed to the
  public ``(M, N, k)`` layout outside the kernel;
- the grid runs blocks of ``block_m`` formations per program; ``block_m``
  shrinks automatically as N grows so the ``(block_m, Np, Np)``
  intermediates (distance matrix, broadcast planes, selection masks)
  stay within the VMEM budget.

The reference has no neighbor search at all (its interaction graph is the
static ring, reference simulate.py:162-167); this op exists for the new
large-swarm capability and matches ``ops.knn.knn`` bit-for-bit in its
selection and masking semantics (see tests/test_ops_pallas.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from marl_distributedformation_tpu.ops.knn import _SELF_MASK

Array = jax.Array

_LANE = 128
_VMEM_BUDGET = 12 * 1024 * 1024  # bytes; ~6 live (block_m, Np, Np) f32 bufs


def padded_n(n: int) -> int:
    return max(_LANE, ((n + _LANE - 1) // _LANE) * _LANE)


def fits_vmem(n: int) -> bool:
    """True when the fused kernel's intermediates fit the VMEM budget even
    at the minimum block_m=1 — the dispatch condition for ``impl="auto"``."""
    np_ = padded_n(n)
    return 6 * 4 * np_ * np_ <= _VMEM_BUDGET


# Auto-dispatch ceiling for the chunked kernel: its resident cost is three
# full (block_m, n_pad) f32 position/validity planes plus the (R, C) tile
# intermediates, and the column loop is a STATIC unroll of n_pad/chunk_c
# chunks (compile time grows O(N * k^2 / chunk_c)). 16384 points keeps the
# planes at ~200 KB and the unroll at 32 chunks; beyond that "auto" falls
# back to XLA (explicit impl="pallas_big" still allowed for larger N —
# after Mosaic pads the singleton sublane axis to 8 the planes cost
# ~96 B/point, so VMEM holds to ~10^5 points; expect long compiles).
_BIG_KERNEL_AUTO_MAX_N = 16384


def fits_big_kernel(n: int) -> bool:
    return n <= _BIG_KERNEL_AUTO_MAX_N


def _pad_planes(points: Array, valid, m_pad: int, n_pad: int):
    """Struct-of-arrays prologue shared by both kernels: f32 cast, x/y
    plane split, validity plane, zero-padding to the padded grid shape.

    Planes are shaped ``(m_pad, 1, n_pad)`` — NOT ``(m_pad, n_pad)`` — so
    their block shape ``(block_m, 1, n_pad)`` is always Mosaic-legal: the
    TPU lowering requires the last two block dims be divisible by (8, 128)
    or equal the array dims, and a 2-D ``(block_m, n_pad)`` block violates
    the sublane rule whenever the VMEM budget drives ``block_m`` below 8
    (fused kernel at N in [384, 640], chunked kernel always). The singleton
    axis pins the sublane dim to "equal the array dim" for any block_m.
    Interpret mode never enforces this, so CPU tests can't catch it —
    tests/tpu_compiled_parity.py exercises the compiled shapes on hardware.
    """
    m, n = points.shape[:2]
    pts = points.astype(jnp.float32)
    x = jnp.pad(pts[..., 0], ((0, m_pad - m), (0, n_pad - n)))
    y = jnp.pad(pts[..., 1], ((0, m_pad - m), (0, n_pad - n)))
    if valid is None:
        vm = jnp.ones((m, n), jnp.float32)
    else:
        vm = valid.astype(jnp.float32)
    vm = jnp.pad(vm, ((0, m_pad - m), (0, n_pad - n)))
    return x[:, None, :], y[:, None, :], vm[:, None, :]


def _unpack_outputs(idx, offx, offy, dist, m: int, n: int):
    """Epilogue shared by both kernels: strip padding, move k to the
    trailing axis, re-assemble (M, N, k, 2) offsets — the public
    ``ops.knn.knn`` layout."""
    idx = jnp.swapaxes(idx[:m, :, :n], 1, 2)  # (M, N, k)
    offsets = jnp.stack(
        [
            jnp.swapaxes(offx[:m, :, :n], 1, 2),
            jnp.swapaxes(offy[:m, :, :n], 1, 2),
        ],
        axis=-1,
    )
    dists = jnp.swapaxes(dist[:m, :, :n], 1, 2)
    return idx, offsets, dists


def _knn_kernel(k, x_ref, y_ref, vmask_ref, idx_ref, offx_ref, offy_ref,
                dist_ref):
    """One grid step: k-NN for a ``(B, Np)`` block of formations.

    ``vmask`` is 1.0 for live agent columns, 0.0 for padding/invalid; masked
    columns can never be selected. Slots with no real candidate left (all
    remaining distances at ``_SELF_MASK``) degrade to self-loops
    (idx=i, offset=0, dist=0), mirroring ``ops.knn.knn``'s ``valid`` path.
    """
    x = x_ref[:, 0, :]  # (B, Np); refs carry the Mosaic-layout
    y = y_ref[:, 0, :]  # singleton axis (_pad_planes)
    vm = vmask_ref[:, 0, :]
    d2 = (x[:, :, None] - x[:, None, :]) ** 2 + (
        y[:, :, None] - y[:, None, :]
    ) ** 2  # (B, Np, Np)
    rows = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 2)
    blocked = (rows == cols) | (vm[:, None, :] < 0.5)
    d2 = jnp.where(blocked, _SELF_MASK, d2)
    row_ids = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)  # (B, Np)
    xb = jnp.broadcast_to(x[:, None, :], d2.shape)
    yb = jnp.broadcast_to(y[:, None, :], d2.shape)
    for j in range(k):  # k is small and static: unrolled argmin passes
        best = jnp.min(d2, axis=2)  # (B, Np)
        amin = jnp.argmin(d2, axis=2).astype(jnp.int32)
        real = best < 0.5 * _SELF_MASK
        onehot = cols == amin[:, :, None]  # exactly one column per row
        nx = jnp.sum(jnp.where(onehot, xb, 0.0), axis=2)
        ny = jnp.sum(jnp.where(onehot, yb, 0.0), axis=2)
        idx_ref[:, j, :] = jnp.where(real, amin, row_ids)
        offx_ref[:, j, :] = jnp.where(real, nx - x, 0.0)
        offy_ref[:, j, :] = jnp.where(real, ny - y, 0.0)
        dist_ref[:, j, :] = jnp.where(
            real, jnp.sqrt(jnp.maximum(best, 0.0)), 0.0
        )
        d2 = jnp.where(onehot, _SELF_MASK, d2)  # exclude from later passes


def _knn_kernel_chunked(
    k, chunk_c, x_rows_ref, y_rows_ref, x_cols_ref, y_cols_ref, vm_ref,
    idx_ref, offx_ref, offy_ref, dist_ref,
):
    """Grid step for the big-N kernel: k-NN for a ``(B, R)`` block of query
    rows against the full ``(B, Np)`` point set, streamed in ``chunk_c``-
    column chunks so VMEM holds ``(B, R, C)`` — never ``(B, Np, Np)``.

    Running best-k state is a bubble-insertion sorted list (k small): each
    chunk contributes its k best via argmin passes, and every candidate is
    inserted with a strict ``<`` compare — equal distances never displace
    an earlier (lower-column) candidate, which reproduces ``lax.top_k``'s
    stable tie-breaking, so results are bit-identical to the XLA path.
    """
    b, _, r_block = x_rows_ref.shape  # refs carry the Mosaic-layout
    n_pad = x_cols_ref.shape[2]  # singleton axis (_pad_planes)
    xr = x_rows_ref[:, 0, :]  # (B, R)
    yr = y_rows_ref[:, 0, :]
    rb = pl.program_id(1)
    row_gids = rb * r_block + jax.lax.broadcasted_iota(
        jnp.int32, (b, r_block), 1
    )

    zero_f = jnp.zeros((b, r_block), jnp.float32)
    best_d = [zero_f + _SELF_MASK for _ in range(k)]
    best_i = [jnp.zeros((b, r_block), jnp.int32) for _ in range(k)]
    best_x = [zero_f for _ in range(k)]
    best_y = [zero_f for _ in range(k)]

    for c in range(n_pad // chunk_c):  # static unroll over column chunks
        sl = slice(c * chunk_c, (c + 1) * chunk_c)
        xc = x_cols_ref[:, 0, sl]  # (B, C)
        yc = y_cols_ref[:, 0, sl]
        vmc = vm_ref[:, 0, sl]
        d2 = (xr[:, :, None] - xc[:, None, :]) ** 2 + (
            yr[:, :, None] - yc[:, None, :]
        ) ** 2  # (B, R, C)
        local_cols = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 2)
        global_cols = local_cols + c * chunk_c
        blocked = (global_cols == row_gids[:, :, None]) | (
            vmc[:, None, :] < 0.5
        )
        d2 = jnp.where(blocked, _SELF_MASK, d2)
        xcb = jnp.broadcast_to(xc[:, None, :], d2.shape)
        ycb = jnp.broadcast_to(yc[:, None, :], d2.shape)
        for _ in range(k):  # chunk's k best, ascending
            cd = jnp.min(d2, axis=2)
            am = jnp.argmin(d2, axis=2).astype(jnp.int32)
            onehot = local_cols == am[:, :, None]
            ci = c * chunk_c + am
            cx = jnp.sum(jnp.where(onehot, xcb, 0.0), axis=2)
            cy = jnp.sum(jnp.where(onehot, ycb, 0.0), axis=2)
            d2 = jnp.where(onehot, _SELF_MASK, d2)
            for j in range(k):  # bubble-insert into the sorted running k
                # Lexicographic (distance, column) compare: a strict '<'
                # alone would let a displaced lower-column element get
                # stuck behind an equal-distance one, reordering ties vs
                # lax.top_k's stable lower-index preference.
                take = (cd < best_d[j]) | (
                    (cd == best_d[j]) & (ci < best_i[j])
                )
                best_d[j], cd = (
                    jnp.where(take, cd, best_d[j]),
                    jnp.where(take, best_d[j], cd),
                )
                best_i[j], ci = (
                    jnp.where(take, ci, best_i[j]),
                    jnp.where(take, best_i[j], ci),
                )
                best_x[j], cx = (
                    jnp.where(take, cx, best_x[j]),
                    jnp.where(take, best_x[j], cx),
                )
                best_y[j], cy = (
                    jnp.where(take, cy, best_y[j]),
                    jnp.where(take, best_y[j], cy),
                )

    for j in range(k):
        real = best_d[j] < 0.5 * _SELF_MASK
        idx_ref[:, j, :] = jnp.where(real, best_i[j], row_gids)
        offx_ref[:, j, :] = jnp.where(real, best_x[j] - xr, 0.0)
        offy_ref[:, j, :] = jnp.where(real, best_y[j] - yr, 0.0)
        dist_ref[:, j, :] = jnp.where(
            real, jnp.sqrt(jnp.maximum(best_d[j], 0.0)), 0.0
        )


@functools.partial(
    jax.jit,
    static_argnames=("k", "block_r", "chunk_c", "block_m", "interpret"),
)
def knn_batch_pallas_big(
    points: Array,
    k: int,
    valid: Optional[Array] = None,
    block_r: int = 256,
    chunk_c: int = 512,
    block_m: int = 1,
    interpret: bool = False,
) -> Tuple[Array, Array, Array]:
    """Batched k-NN for swarms past the fused kernel's VMEM cliff
    (``fits_vmem`` fails for N > 640): streams the distance matrix in
    ``(block_r, chunk_c)`` tiles with a running top-k. The ``(M, N, N)``
    tensor never exists anywhere — not in HBM either, unlike the XLA
    fallback. VMEM holds the tile intermediates plus three full
    ``(block_m, 1, n_pad)`` position/validity planes (~96 B/point: Mosaic
    pads the singleton sublane axis to 8, so each f32 plane costs
    32 B/point — fine to ~10^5 points), and the chunk loop is a static
    unroll of
    ``n_pad/chunk_c`` iterations, so compile time grows with N;
    ``impl="auto"`` caps this path at N <= 16384 (``fits_big_kernel``).
    Output layout and selection semantics are identical to
    ``knn_batch_pallas`` / ``ops.knn.knn`` (ties break toward the lower
    index).

    ``block_r``/``chunk_c`` must be lane-aligned (multiples of 128); N pads
    to their lcm. Defaults stream ~3 MB of VMEM intermediates per program.
    """
    m, n, d = points.shape
    assert d == 2, f"knn_batch_pallas_big is 2-D only, got d={d}"
    assert k < n, f"knn needs k < N (k={k}, N={n})"
    assert block_r % 128 == 0 and chunk_c % 128 == 0, (
        f"block_r/chunk_c must be multiples of 128, got {block_r}/{chunk_c}"
    )
    import math

    step = math.lcm(block_r, chunk_c)
    n_pad = ((n + step - 1) // step) * step
    m_pad = ((m + block_m - 1) // block_m) * block_m
    x, y, vm = _pad_planes(points, valid, m_pad, n_pad)

    rows_plane = pl.BlockSpec(
        (block_m, 1, block_r), lambda i, r: (i, 0, r), memory_space=pltpu.VMEM
    )
    cols_plane = pl.BlockSpec(
        (block_m, 1, n_pad), lambda i, r: (i, 0, 0), memory_space=pltpu.VMEM
    )
    out_plane = pl.BlockSpec(
        (block_m, k, block_r),
        lambda i, r: (i, 0, r),
        memory_space=pltpu.VMEM,
    )
    out_f32 = jax.ShapeDtypeStruct((m_pad, k, n_pad), jnp.float32)
    idx, offx, offy, dist = pl.pallas_call(
        functools.partial(_knn_kernel_chunked, k, chunk_c),
        grid=(m_pad // block_m, n_pad // block_r),
        in_specs=[rows_plane, rows_plane, cols_plane, cols_plane, cols_plane],
        out_specs=[out_plane] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, k, n_pad), jnp.int32),
            out_f32,
            out_f32,
            out_f32,
        ],
        interpret=interpret,
    )(x, y, x, y, vm)
    return _unpack_outputs(idx, offx, offy, dist, m, n)


@functools.partial(jax.jit, static_argnames=("k", "block_m", "interpret"))
def knn_batch_pallas(
    points: Array,
    k: int,
    valid: Optional[Array] = None,
    block_m: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[Array, Array, Array]:
    """Batched k nearest neighbors, fused on-chip.

    Args:
      points: ``(M, N, 2)`` positions for M independent formations.
      k: neighbor count, ``k < N``.
      valid: optional ``(M, N)`` bool mask; invalid points are never
        selected and short rows degrade to self-loops (same contract as
        ``ops.knn.knn``).
      block_m: formations per kernel program. Default: scaled so the
        ~6 live ``(block_m, Np, Np)`` f32 intermediates stay under ~12 MB
        of VMEM (8 formations/program at Np=128, 1 at Np >= 512).
      interpret: run in Pallas interpret mode (CPU tests).

    Returns:
      ``(idx (M, N, k) int32, offsets (M, N, k, 2), dists (M, N, k))``,
      sorted by ascending distance — the ``ops.knn.knn`` layout.
    """
    m, n, d = points.shape
    assert d == 2, f"knn_batch_pallas is 2-D only, got d={d}"
    assert k < n, f"knn needs k < N (k={k}, N={n})"
    n_pad = padded_n(n)
    if not fits_vmem(n):
        raise ValueError(
            f"knn_batch_pallas: N={n} (padded {n_pad}) needs "
            f"~{6 * 4 * n_pad * n_pad >> 20} MB of VMEM intermediates even "
            f"at block_m=1 (budget {_VMEM_BUDGET >> 20} MB); use the XLA "
            "path (knn_batch(..., impl='xla') / EnvParams.knn_impl='xla')"
        )
    if block_m is None:
        # ~6 live (block_m, Np, Np) f32 intermediates (d2, xb, yb, masks)
        # under the VMEM budget.
        block_m = max(1, min(8, _VMEM_BUDGET // (6 * 4) // (n_pad * n_pad)))
    m_pad = ((m + block_m - 1) // block_m) * block_m
    x, y, vm = _pad_planes(points, valid, m_pad, n_pad)

    plane = pl.BlockSpec(
        (block_m, 1, n_pad), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    out_plane = pl.BlockSpec(
        (block_m, k, n_pad), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    out_f32 = jax.ShapeDtypeStruct((m_pad, k, n_pad), jnp.float32)
    idx, offx, offy, dist = pl.pallas_call(
        functools.partial(_knn_kernel, k),
        grid=(m_pad // block_m,),
        in_specs=[plane, plane, plane],
        out_specs=[out_plane] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, k, n_pad), jnp.int32),
            out_f32,
            out_f32,
            out_f32,
        ],
        interpret=interpret,
    )(x, y, vm)
    return _unpack_outputs(idx, offx, offy, dist, m, n)
