"""k-nearest-neighbor search over agent positions.

BASELINE.json config 4 ("100-agent swarm with k-nearest-neighbor obs graph
+ GNN policy") needs, per formation and per step, each agent's k nearest
neighbors. The reference has nothing like it (its interaction graph is the
static ring, simulate.py:162-167); this op is the new scaling axis for large
swarms.

TPU mapping: the pairwise squared-distance matrix is computed in the direct
broadcast form (x_i - x_j)^2 + (y_i - y_j)^2 — pure VPU elementwise work,
fully fuseable — then ``jax.lax.top_k`` selects the k smallest per row.
Everything is static-shaped and batches cleanly under ``vmap``.

Why NOT the |a|^2 + |b|^2 - 2 a.b matmul expansion: TPU executes f32
matmuls at bf16 input precision by default, and at world-coordinate scale
~400 the expansion subtracts numbers of magnitude ~3e5 to recover
differences of magnitude ~1 — the bf16 rounding of the cross term is
amplified into real errors (measured round 2 on TPU v5e at M=4096, N=100,
k=4: 33.5% wrong neighbor indices, distance errors up to 46 world units vs
float64 ground truth). The direct form subtracts coordinates FIRST, so
there is no cancellation and no matmul precision to worry about; at d=2
the FLOP difference is noise. ``tests/tpu_compiled_parity.py`` pins this
on hardware and ``tests/test_ops_pallas.py::test_xla_knn_precision`` pins
it structurally (no dot_general in the lowering).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from marl_distributedformation_tpu.jax_compat import manual_axis_context

Array = jax.Array

# Self-distance mask. Finite (not inf) so top_k never selects NaN garbage
# even when N <= k would force it into the masked diagonal.
_SELF_MASK = 1e12


def pairwise_sq_dists(points: Array) -> Array:
    """Squared euclidean distance matrix ``(N, N)`` for ``points (N, d)``
    in the direct broadcast form (coordinates subtracted BEFORE squaring —
    exact in f32, no bf16-matmul cancellation; see module docstring); the
    diagonal is masked to ``_SELF_MASK``."""
    diff = points[:, None, :] - points[None, :, :]  # (N, N, d)
    d2 = (diff * diff).sum(-1)
    return d2 + _SELF_MASK * jnp.eye(points.shape[0], dtype=points.dtype)


def knn(
    points: Array, k: int, valid: Array = None
) -> Tuple[Array, Array, Array]:
    """Per-point k nearest neighbors (excluding self).

    Args:
      points: ``(N, d)`` positions (single formation; ``vmap`` over M).
      k: neighbor count, ``k < N``.
      valid: optional ``(N,)`` bool mask for padded formations — invalid
        points are never selected as neighbors. When fewer than k valid
        neighbors exist (a formation padded down to <= k agents), the
        surplus slots degrade to harmless self-loops: ``idx = i``,
        ``offset = 0``, ``dist = 0`` — no masked-distance garbage can reach
        observations.

    Returns:
      ``(idx, offsets, dists)``: indices ``(N, k)`` int32 sorted by
      ascending distance, offsets ``(N, k, d)`` with
      ``offsets[i, j] = points[idx[i, j]] - points[i]``, and euclidean
      distances ``(N, k)``.
    """
    n = points.shape[0]
    assert k < n, f"knn needs k < N (k={k}, N={n})"
    if valid is None:
        # The full search IS the local-query search with every point as a
        # query — a single implementation keeps the sharded/unsharded
        # bit-parity invariant true by construction (parallel/ring.py).
        return knn_local(points, points, k, 0)
    d2 = pairwise_sq_dists(points)
    d2 = jnp.where(valid[None, :], d2, _SELF_MASK)
    neg, idx = jax.lax.top_k(-d2, k)
    idx = idx.astype(jnp.int32)
    # Slots that resolved into the masked region (self or invalid
    # columns, all at _SELF_MASK) become explicit self-loops.
    real = -neg < 0.5 * _SELF_MASK
    idx = jnp.where(real, idx, jnp.arange(n, dtype=jnp.int32)[:, None])
    offsets = points[idx] - points[:, None, :]
    dists = jnp.sqrt(jnp.maximum(-neg, 0.0))
    dists = jnp.where(real, dists, 0.0)
    return idx, offsets, dists


def knn_local(
    queries: Array,
    points: Array,
    k: int,
    query_offset,
) -> Tuple[Array, Array, Array]:
    """k nearest neighbors of a LOCAL block of query agents against the
    full point set — the agent-axis-sharded search (parallel/ring.py swarm
    mode): each device holds ``queries (nq, d)`` (its slab of the formation,
    global rows ``query_offset .. query_offset+nq``) and the all-gathered
    ``points (N, d)``.

    Distances are computed in the same direct broadcast form and the same
    column order as :func:`knn`, so the selected indices/distances are
    bit-identical to the corresponding rows of the unsharded search (no
    tie-break divergence between sharded and unsharded trajectories).

    Returns ``(idx (nq, k) int32 GLOBAL indices, offsets (nq, k, d),
    dists (nq, k))`` sorted by ascending distance.
    """
    nq = queries.shape[0]
    n = points.shape[0]
    assert k < n, f"knn_local needs k < N (k={k}, N={n})"
    diff = queries[:, None, :] - points[None, :, :]  # (nq, N, d)
    d2 = (diff * diff).sum(-1)
    # Self-mask by GLOBAL index: local query row j is global row
    # query_offset + j.
    gids = query_offset + jnp.arange(nq, dtype=jnp.int32)
    cols = jnp.arange(n, dtype=jnp.int32)
    d2 = jnp.where(cols[None, :] == gids[:, None], _SELF_MASK, d2)
    neg, idx = jax.lax.top_k(-d2, k)
    idx = idx.astype(jnp.int32)
    offsets = points[idx] - queries[:, None, :]
    dists = jnp.sqrt(jnp.maximum(-neg, 0.0))
    return idx, offsets, dists


def _resolve_auto_impl(points: Array) -> str:
    """The ``impl="auto"`` dispatch predicate, factored out so tests can
    pin the backend: on TPU, the fused kernel when the whole per-formation
    problem fits VMEM (N <= 640), the chunked-streaming kernel beyond that
    (no N ceiling); xla on other backends or when the SPMD partitioner
    controls the batch (a pallas_call is a Mosaic custom call it cannot
    split; shard_map-wrapped callers re-enter with local blocks)."""
    from marl_distributedformation_tpu.ops.knn_pallas import (
        fits_big_kernel,
        fits_vmem,
    )

    if jax.default_backend() != "tpu" or _spmd_partitioner_controlled(
        points
    ):
        return "xla"
    n = points.shape[1]
    if fits_vmem(n):
        return "pallas"
    # The chunked kernel's column loop is a static unroll — auto caps it
    # where compile time stays sane (explicit impl="pallas_big" can go
    # further; see knn_batch_pallas_big).
    return "pallas_big" if fits_big_kernel(n) else "xla"


def _spmd_partitioner_controlled(points: Array) -> bool:
    """True when ``points`` lives on (or is traced under) a multi-device
    mesh whose axes the XLA SPMD partitioner controls.

    Concrete arrays are easy on every JAX: committed to >1 device means
    the implicit jit around the kernel would need the partitioner -> True.
    Tracers split by JAX generation:

    - sharding-in-types avals (jax >= 0.6): aval mesh non-empty with any
      Auto/Explicit axis (plain ``jit`` under a mesh) -> the partitioner
      will place this op -> True; under ``shard_map`` (all axes Manual)
      or with no mesh -> the kernel sees a per-device local block ->
      False.
    - legacy avals (jax <= 0.4.x, no sharding on tracers): inside
      ``shard_map``/``pmap`` the mesh axes are bound as named axis frames
      (``jax_compat.manual_axis_context``) -> local block -> False;
      under plain ``jit`` the tracer cannot reveal its placement, so on a
      multi-device process we conservatively assume the partitioner may
      control it -> True (sharded training re-enters through the
      shard_map wrappers in ``parallel/``, where Pallas is selected
      again; only a single-process plain-jit multi-device run pays the
      xla fallback). Single device -> False.
    """
    if not isinstance(points, jax.core.Tracer):
        sharding = getattr(points, "sharding", None)
        return sharding is not None and len(sharding.device_set) > 1
    aval = getattr(points, "aval", None)
    aval_sharding = getattr(aval, "sharding", None)
    if aval_sharding is not None:
        mesh = getattr(aval_sharding, "mesh", None)
        if mesh is None or not getattr(mesh, "axis_types", None):
            return False
        axis_type = jax.sharding.AxisType
        return any(t != axis_type.Manual for t in mesh.axis_types)
    if manual_axis_context():
        return False
    return len(jax.devices()) > 1


def knn_batch(
    points: Array,
    k: int,
    valid: Array = None,
    impl: str = "auto",
) -> Tuple[Array, Array, Array]:
    """Batched k-NN over ``points (M, N, 2)`` with implementation dispatch.

    ``impl``: ``"xla"`` — ``vmap`` of :func:`knn` (works everywhere);
    ``"pallas"`` — the fused TPU kernel (ops/knn_pallas.py), which never
    materializes the ``(M, N, N)`` distance tensor in HBM;
    ``"pallas_big"`` — the chunked-streaming kernel for swarms past the
    fused kernel's VMEM cliff (N > 640; O(block) VMEM regardless of N);
    ``"pallas_interpret"`` / ``"pallas_big_interpret"`` — the same kernels
    in interpret mode (CPU tests);
    ``"auto"`` — on TPU, pallas when the kernel's intermediates fit VMEM
    (N <= 640: 641 pads to 768 lanes and the ~6 live (1, 768, 768) f32
    intermediates exceed the 12 MiB budget), pallas_big for
    640 < N <= 16384 (the static chunk unroll keeps compile time bounded;
    ``fits_big_kernel``), xla beyond — provided the batch is not under
    SPMD-partitioner control
    (a ``pallas_call`` is a Mosaic custom call the partitioner cannot split,
    so a dp-sharded batch traced under plain ``jit`` falls back to xla;
    inside ``shard_map`` — where the kernel sees its local block — pallas is
    selected again; ``parallel.make_dp_step`` provides that wrapping for
    sharded training).
    """
    if impl == "auto":
        impl = _resolve_auto_impl(points)
    if impl in ("pallas", "pallas_interpret"):
        from marl_distributedformation_tpu.ops.knn_pallas import (
            knn_batch_pallas,
        )

        return knn_batch_pallas(
            points, k, valid, interpret=(impl == "pallas_interpret")
        )
    if impl in ("pallas_big", "pallas_big_interpret"):
        from marl_distributedformation_tpu.ops.knn_pallas import (
            knn_batch_pallas_big,
        )

        return knn_batch_pallas_big(
            points, k, valid, interpret=(impl == "pallas_big_interpret")
        )
    assert impl == "xla", f"unknown knn impl {impl!r}"
    if valid is None:
        return jax.vmap(lambda p: knn(p, k))(points)
    return jax.vmap(lambda p, v: knn(p, k, v))(points, valid)
