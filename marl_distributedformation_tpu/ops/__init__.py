"""TPU compute ops: k-NN neighbor search."""

from marl_distributedformation_tpu.ops.knn import (  # noqa: F401
    knn,
    pairwise_sq_dists,
)
