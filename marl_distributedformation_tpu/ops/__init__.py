"""TPU compute ops: k-NN neighbor search (XLA and fused Pallas paths)."""

from marl_distributedformation_tpu.ops.knn import (  # noqa: F401
    knn,
    knn_batch,
    knn_local,
    pairwise_sq_dists,
)
