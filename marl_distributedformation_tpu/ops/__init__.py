"""TPU compute ops: k-NN neighbor search (XLA path; fused Pallas kernel
for N <= 640; chunked-streaming Pallas kernel beyond; local-query variant
for agent-axis sharding)."""

from marl_distributedformation_tpu.ops.knn import (  # noqa: F401
    knn,
    knn_batch,
    knn_local,
    pairwise_sq_dists,
)
