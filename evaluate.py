#!/usr/bin/env python
"""Quantitative policy evaluation — the capability the reference lacks
entirely (its only evaluation is watching animations, SURVEY.md §4).

Rolls full episodes for M formations in one jitted scan and prints a
comparison table: trained policy vs the scripted potential-field baseline
(env/baseline.py = reference simulate.py:256-319) vs zero actions, on
identical initial states. Emits one JSON line for machine consumption.

Usage:
    python evaluate.py name=myrun                  # latest checkpoint of run
    python evaluate.py checkpoint=logs/x/rl_model_200_steps.ckpt
    python evaluate.py name=myrun eval_formations=1024 eval_seed=7
    python evaluate.py name=myrun scenario=wind scenario_severity=0.5
                                                   # robustness: evaluate
                                                   # under a disturbance
                                                   # scenario (scenarios/)

Unknown override keys and unknown scenario names fail fast with the valid
entries — a typo must never silently evaluate the clean default.
"""

from __future__ import annotations

import json
import re
import sys

from marl_distributedformation_tpu.eval import (
    baseline_act_fn,
    evaluate,
    evaluate_checkpoint,
    zero_act_fn,
)
from marl_distributedformation_tpu.utils import (
    env_params_from_config,
    latest_checkpoint,
    load_config,
    repo_root,
    setup_platform,
    validate_override_keys,
)

# Keys meaningful to this entry point beyond the YAML config defaults.
EVAL_KEYS = (
    "checkpoint",
    "eval_formations",
    "eval_seed",
    "eval_deterministic",
    "scenario",
)


def _scenario_params(cfg, overrides):
    """Resolve ``scenario=NAME`` (+ ``scenario_severity``) to traced
    ScenarioParams — unknown names exit naming the registry entries.

    Two near-miss spellings that would otherwise pass key validation
    (both are real YAML keys) and silently evaluate the CLEAN env are
    rejected explicitly: the plural training key ``scenarios=``, and a
    ``scenario_severity=`` override with no ``scenario=`` to apply it to.
    """
    name = cfg.get("scenario")
    override_keys = {
        o.split("=", 1)[0] for o in overrides if "=" in o
    }
    if "scenarios" in override_keys:
        raise SystemExit(
            "evaluate.py takes the SINGULAR scenario=<name> (scenarios= "
            "is the train.py domain-randomization key and would be "
            "ignored here); e.g. scenario=wind scenario_severity=0.5"
        )
    if not name:
        if "scenario_severity" in override_keys:
            raise SystemExit(
                "scenario_severity=... was given without scenario=<name> "
                "— it would silently apply to nothing; add scenario=<name>"
            )
        return None, None, None
    from marl_distributedformation_tpu.scenarios import scenario_params_for

    severity = float(cfg.get("scenario_severity", 0.5) or 0.0)
    try:
        return scenario_params_for(str(name), severity), str(name), severity
    except ValueError as e:
        raise SystemExit(str(e)) from e


def _resolved_backend() -> dict:
    """What actually ran — an eval JSON banked as hardware evidence must
    prove its backend from the record itself (cf. train.py's
    ``_snapshot_config``; a tunnel drop silently falls back to CPU)."""
    try:
        import jax

        dev = jax.devices()[0]
        return {
            "resolved_platform": dev.platform,
            "resolved_device": dev.device_kind,
        }
    except Exception:  # noqa: BLE001 — provenance never kills an eval
        return {}


def main(argv=None) -> dict:
    overrides = sys.argv[1:] if argv is None else argv
    # Fail fast on mistyped keys: this entry point has no config snapshot
    # to surface a typo, and an ignored key means evaluating the wrong
    # thing (e.g. the clean env instead of the requested scenario).
    validate_override_keys(overrides, extra_keys=EVAL_KEYS)
    cfg = load_config(overrides)
    setup_platform(cfg.get("platform"))
    params = env_params_from_config(cfg)
    m = int(cfg.get("eval_formations", 1024))
    seed = int(cfg.get("eval_seed", 1234))
    sp, scenario_name, severity = _scenario_params(cfg, overrides)

    # eval_deterministic=false evaluates the policy as it behaves during
    # training (actions sampled from its Gaussian) — SB3's
    # evaluate_policy(deterministic=...) knob. Policies trained with a
    # high entropy bonus can rely on their action noise; the mode action
    # alone can misrepresent them (see docs/acceptance/hetero5/). Values
    # arrive YAML-parsed, so plain truthiness is the repo convention.
    det = bool(cfg.get("eval_deterministic", True))

    ckpt = cfg.get("checkpoint")
    if not ckpt:
        log_dir = repo_root() / "logs" / str(cfg.name)
        # Strictly seed<N> DIRECTORIES: stray files or backups like
        # seed0.bak must neither crash the sort nor flip a single run
        # into sweep mode.
        member_dirs = sorted(
            (
                p for p in log_dir.glob("seed*")
                if p.is_dir() and re.fullmatch(r"seed\d+", p.name)
            ),
            key=lambda p: int(p.name.removeprefix("seed")),
        )
        if member_dirs:
            # Sweep run (train/sweep.py): rank EVERY member by held-out
            # evaluation on identical initial states — more principled
            # than sweep_summary.json's training-reward ranking.
            return eval_sweep(
                member_dirs, params, m, seed, det,
                scenario_params=sp, scenario=scenario_name,
                severity=severity,
            )
        ckpt = latest_checkpoint(log_dir)
        if ckpt is None:
            raise SystemExit(
                f"no checkpoint under {log_dir}; pass checkpoint=... or "
                "name=<trained run>"
            )

    rows = {
        "policy": evaluate_checkpoint(
            str(ckpt), params, m, seed, det, scenario_params=sp
        ),
        "baseline": evaluate(
            baseline_act_fn(params), params, m, seed, scenario_params=sp
        ),
        "zero": evaluate(
            zero_act_fn(), params, m, seed, scenario_params=sp
        ),
    }

    cols = [
        "episode_return_per_agent",
        "final_avg_dist_to_goal",
        "last100_avg_dist_to_goal",
        "final_ave_dist_to_neighbor",
    ]
    name_w = max(len(k) for k in rows)
    print(f"[eval] checkpoint: {ckpt}")
    print(f"[eval] M={m} formations x N={params.num_agents} agents, "
          f"seed={seed}, full episodes")
    if scenario_name:
        print(f"[eval] scenario={scenario_name} severity={severity:g}")
    header = " | ".join(f"{c:>26}" for c in cols)
    print(f"{'':<{name_w}} | {header}")
    for name, r in rows.items():
        vals = " | ".join(f"{r[c]:>26.2f}" for c in cols)
        print(f"{name:<{name_w}} | {vals}")

    result = {
        "checkpoint": str(ckpt),
        "eval_formations": m,
        "num_agents": params.num_agents,
        "seed": seed,
        "eval_deterministic": det,
        **(
            {"scenario": scenario_name, "scenario_severity": severity}
            if scenario_name
            else {}
        ),
        **{f"{name}_{c}": r[c] for name, r in rows.items() for c in cols},
        "beats_baseline": bool(
            rows["policy"]["episode_return_per_agent"]
            > rows["baseline"]["episode_return_per_agent"]
        ),
        **_resolved_backend(),
    }
    print(json.dumps(result))
    return result


def eval_sweep(
    member_dirs, params, m: int, seed: int, deterministic: bool = True,
    scenario_params=None, scenario=None, severity=None,
) -> dict:
    """Evaluate every sweep member's latest checkpoint plus the baseline
    and zero policies, all on the same initial states; print a ranked
    table and emit one JSON line."""
    rows = {}
    for d in member_dirs:
        ckpt = latest_checkpoint(d)
        if ckpt is None:
            print(f"[eval] {d.name}: no checkpoint, skipping")
            continue
        rows[d.name] = evaluate_checkpoint(
            str(ckpt), params, m, seed, deterministic,
            scenario_params=scenario_params,
        )
    if not rows:
        raise SystemExit("no member checkpoints found under seed*/")
    rows["baseline"] = evaluate(
        baseline_act_fn(params), params, m, seed,
        scenario_params=scenario_params,
    )
    rows["zero"] = evaluate(
        zero_act_fn(), params, m, seed, scenario_params=scenario_params
    )

    key = "episode_return_per_agent"
    ranked = sorted(rows, key=lambda n: rows[n][key], reverse=True)
    members = [n for n in ranked if n.startswith("seed")]
    best = members[0]
    print(f"[eval] sweep: {len(members)} members, M={m} formations x "
          f"N={params.num_agents} agents, seed={seed}, full episodes")
    name_w = max(len(n) for n in rows)
    print(f"{'':<{name_w}} | {key:>26} | final_avg_dist_to_goal")
    for n in ranked:
        marker = " <- best member" if n == best else ""
        print(f"{n:<{name_w}} | {rows[n][key]:>26.2f} | "
              f"{rows[n]['final_avg_dist_to_goal']:>22.2f}{marker}")

    result = {
        "sweep_members": len(members),
        "eval_formations": m,
        "num_agents": params.num_agents,
        "seed": seed,
        "eval_deterministic": deterministic,
        **(
            {"scenario": scenario, "scenario_severity": severity}
            if scenario
            else {}
        ),
        "member_returns": {n: rows[n][key] for n in members},
        "best_member": best,
        "best_return": rows[best][key],
        "baseline_return": rows["baseline"][key],
        "beats_baseline": bool(rows[best][key] > rows["baseline"][key]),
        **_resolved_backend(),
    }
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
