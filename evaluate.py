#!/usr/bin/env python
"""Quantitative policy evaluation — the capability the reference lacks
entirely (its only evaluation is watching animations, SURVEY.md §4).

Rolls full episodes for M formations in one jitted scan and prints a
comparison table: trained policy vs the scripted potential-field baseline
(env/baseline.py = reference simulate.py:256-319) vs zero actions, on
identical initial states. Emits one JSON line for machine consumption.

Usage:
    python evaluate.py name=myrun                  # latest checkpoint of run
    python evaluate.py checkpoint=logs/x/rl_model_200_steps.ckpt
    python evaluate.py name=myrun eval_formations=1024 eval_seed=7
"""

from __future__ import annotations

import json
import re
import sys

from marl_distributedformation_tpu.eval import (
    baseline_act_fn,
    evaluate,
    evaluate_checkpoint,
    zero_act_fn,
)
from marl_distributedformation_tpu.utils import (
    env_params_from_config,
    latest_checkpoint,
    load_config,
    repo_root,
    setup_platform,
)


def _resolved_backend() -> dict:
    """What actually ran — an eval JSON banked as hardware evidence must
    prove its backend from the record itself (cf. train.py's
    ``_snapshot_config``; a tunnel drop silently falls back to CPU)."""
    try:
        import jax

        dev = jax.devices()[0]
        return {
            "resolved_platform": dev.platform,
            "resolved_device": dev.device_kind,
        }
    except Exception:  # noqa: BLE001 — provenance never kills an eval
        return {}


def main(argv=None) -> dict:
    cfg = load_config(sys.argv[1:] if argv is None else argv)
    setup_platform(cfg.get("platform"))
    params = env_params_from_config(cfg)
    m = int(cfg.get("eval_formations", 1024))
    seed = int(cfg.get("eval_seed", 1234))

    # eval_deterministic=false evaluates the policy as it behaves during
    # training (actions sampled from its Gaussian) — SB3's
    # evaluate_policy(deterministic=...) knob. Policies trained with a
    # high entropy bonus can rely on their action noise; the mode action
    # alone can misrepresent them (see docs/acceptance/hetero5/). Values
    # arrive YAML-parsed, so plain truthiness is the repo convention.
    det = bool(cfg.get("eval_deterministic", True))

    ckpt = cfg.get("checkpoint")
    if not ckpt:
        log_dir = repo_root() / "logs" / str(cfg.name)
        # Strictly seed<N> DIRECTORIES: stray files or backups like
        # seed0.bak must neither crash the sort nor flip a single run
        # into sweep mode.
        member_dirs = sorted(
            (
                p for p in log_dir.glob("seed*")
                if p.is_dir() and re.fullmatch(r"seed\d+", p.name)
            ),
            key=lambda p: int(p.name.removeprefix("seed")),
        )
        if member_dirs:
            # Sweep run (train/sweep.py): rank EVERY member by held-out
            # evaluation on identical initial states — more principled
            # than sweep_summary.json's training-reward ranking.
            return eval_sweep(member_dirs, params, m, seed, det)
        ckpt = latest_checkpoint(log_dir)
        if ckpt is None:
            raise SystemExit(
                f"no checkpoint under {log_dir}; pass checkpoint=... or "
                "name=<trained run>"
            )

    rows = {
        "policy": evaluate_checkpoint(str(ckpt), params, m, seed, det),
        "baseline": evaluate(baseline_act_fn(params), params, m, seed),
        "zero": evaluate(zero_act_fn(), params, m, seed),
    }

    cols = [
        "episode_return_per_agent",
        "final_avg_dist_to_goal",
        "last100_avg_dist_to_goal",
        "final_ave_dist_to_neighbor",
    ]
    name_w = max(len(k) for k in rows)
    print(f"[eval] checkpoint: {ckpt}")
    print(f"[eval] M={m} formations x N={params.num_agents} agents, "
          f"seed={seed}, full episodes")
    header = " | ".join(f"{c:>26}" for c in cols)
    print(f"{'':<{name_w}} | {header}")
    for name, r in rows.items():
        vals = " | ".join(f"{r[c]:>26.2f}" for c in cols)
        print(f"{name:<{name_w}} | {vals}")

    result = {
        "checkpoint": str(ckpt),
        "eval_formations": m,
        "num_agents": params.num_agents,
        "seed": seed,
        "eval_deterministic": det,
        **{f"{name}_{c}": r[c] for name, r in rows.items() for c in cols},
        "beats_baseline": bool(
            rows["policy"]["episode_return_per_agent"]
            > rows["baseline"]["episode_return_per_agent"]
        ),
        **_resolved_backend(),
    }
    print(json.dumps(result))
    return result


def eval_sweep(
    member_dirs, params, m: int, seed: int, deterministic: bool = True
) -> dict:
    """Evaluate every sweep member's latest checkpoint plus the baseline
    and zero policies, all on the same initial states; print a ranked
    table and emit one JSON line."""
    rows = {}
    for d in member_dirs:
        ckpt = latest_checkpoint(d)
        if ckpt is None:
            print(f"[eval] {d.name}: no checkpoint, skipping")
            continue
        rows[d.name] = evaluate_checkpoint(
            str(ckpt), params, m, seed, deterministic
        )
    if not rows:
        raise SystemExit("no member checkpoints found under seed*/")
    rows["baseline"] = evaluate(baseline_act_fn(params), params, m, seed)
    rows["zero"] = evaluate(zero_act_fn(), params, m, seed)

    key = "episode_return_per_agent"
    ranked = sorted(rows, key=lambda n: rows[n][key], reverse=True)
    members = [n for n in ranked if n.startswith("seed")]
    best = members[0]
    print(f"[eval] sweep: {len(members)} members, M={m} formations x "
          f"N={params.num_agents} agents, seed={seed}, full episodes")
    name_w = max(len(n) for n in rows)
    print(f"{'':<{name_w}} | {key:>26} | final_avg_dist_to_goal")
    for n in ranked:
        marker = " <- best member" if n == best else ""
        print(f"{n:<{name_w}} | {rows[n][key]:>26.2f} | "
              f"{rows[n]['final_avg_dist_to_goal']:>22.2f}{marker}")

    result = {
        "sweep_members": len(members),
        "eval_formations": m,
        "num_agents": params.num_agents,
        "seed": seed,
        "eval_deterministic": deterministic,
        "member_returns": {n: rows[n][key] for n in members},
        "best_member": best,
        "best_return": rows[best][key],
        "baseline_return": rows["baseline"][key],
        "beats_baseline": bool(rows[best][key] > rows["baseline"][key]),
        **_resolved_backend(),
    }
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
