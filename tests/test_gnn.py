"""k-NN observation graph + GNN policy tests (BASELINE.json config 4)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.formation import (
    compute_obs,
    reset_batch,
    step_batch,
)
from marl_distributedformation_tpu.models import GNNActorCritic
from marl_distributedformation_tpu.models.gnn import gather_nodes, parse_knn_obs
from marl_distributedformation_tpu.ops import knn
from marl_distributedformation_tpu.train import TrainConfig, Trainer


def _brute_force_knn(points: np.ndarray, k: int):
    n = points.shape[0]
    d = np.linalg.norm(points[:, None] - points[None, :], axis=-1)
    np.fill_diagonal(d, np.inf)
    idx = np.argsort(d, axis=1)[:, :k]
    return idx, d[np.arange(n)[:, None], idx]


def test_knn_matches_brute_force():
    pts = np.asarray(
        jax.random.uniform(jax.random.PRNGKey(3), (50, 2)) * 400.0
    )
    idx, offsets, dists = jax.jit(knn, static_argnums=1)(jnp.asarray(pts), 5)
    ref_idx, ref_d = _brute_force_knn(pts, 5)
    np.testing.assert_array_equal(np.asarray(idx), ref_idx)
    # fp32 |a|^2+|b|^2-2ab expansion loses ~2^-13 relative at coordinate
    # scale 400 — compare with an absolute tolerance in world units.
    np.testing.assert_allclose(np.asarray(dists), ref_d, atol=0.05)
    np.testing.assert_allclose(
        np.asarray(offsets),
        pts[ref_idx] - pts[:, None, :],
        rtol=1e-4,
        atol=1e-4,
    )


def test_knn_valid_mask_excludes_points():
    pts = jnp.arange(12, dtype=jnp.float32).reshape(6, 2)
    valid = jnp.array([True, True, True, True, False, False])
    idx, _, _ = knn(pts, 3, valid=valid)
    assert not np.isin(np.asarray(idx), [4, 5]).any()


def test_knn_fewer_valid_than_k_degrades_to_self_loops():
    # Only 3 valid points but k=3: each has 2 real neighbors; the surplus
    # slot must be a harmless self-loop, never an invalid index or a
    # masked-distance blowup.
    pts = jnp.array(
        [[0.0, 0.0], [10.0, 0.0], [0.0, 10.0], [99.0, 99.0], [98.0, 98.0]]
    )
    valid = jnp.array([True, True, True, False, False])
    idx, offsets, dists = knn(pts, 3, valid=valid)
    idx, offsets, dists = (np.asarray(idx), np.asarray(offsets), np.asarray(dists))
    for i in range(3):
        assert not np.isin(idx[i], [3, 4]).any()
        assert idx[i, 2] == i  # surplus slot -> self
        np.testing.assert_array_equal(offsets[i, 2], 0.0)
        assert dists[i, 2] == 0.0
    assert dists[:3].max() < 100.0  # no 1e6 garbage anywhere


def test_knn_obs_layout():
    params = EnvParams(num_agents=10, obs_mode="knn", knn_k=3)
    assert params.obs_dim == 2 + 6 + 3 + 2 + 3
    state = reset_batch(jax.random.PRNGKey(0), params, 2)
    obs = jax.vmap(compute_obs, in_axes=(0, 0, None))(
        state.agents, state.goal, params
    )
    assert obs.shape == (2, 10, params.obs_dim)

    # Own normalized position block.
    wh = np.array([params.width, params.height])
    np.testing.assert_allclose(
        np.asarray(obs[0, :, :2]), np.asarray(state.agents[0]) / wh, rtol=1e-5
    )
    # Index block: valid agent ids, never self.
    idx = np.asarray(obs[0, :, -3:]).astype(int)
    assert ((idx >= 0) & (idx < 10)).all()
    assert (idx != np.arange(10)[:, None]).all()
    # Offset block consistent with the indices it names.
    agents = np.asarray(state.agents[0])
    offsets = np.asarray(obs[0, :, 2:8]).reshape(10, 3, 2) * wh
    np.testing.assert_allclose(
        offsets, agents[idx] - agents[:, None, :], rtol=1e-4, atol=1e-3
    )


def test_knn_env_steps_at_100_agents():
    params = EnvParams(num_agents=100, obs_mode="knn", knn_k=8)
    state = reset_batch(jax.random.PRNGKey(1), params, 4)
    vel = jnp.zeros((4, 100, 2))
    state, tr = jax.jit(step_batch, static_argnums=2)(state, vel, params)
    assert tr.obs.shape == (4, 100, params.obs_dim)
    assert np.isfinite(np.asarray(tr.obs)).all()
    assert np.isfinite(np.asarray(tr.reward)).all()


def test_gnn_shapes_and_locality():
    k, n = 3, 12
    params = EnvParams(num_agents=n, obs_mode="knn", knn_k=k)
    state = reset_batch(jax.random.PRNGKey(2), params, 1)
    obs = jax.vmap(compute_obs, in_axes=(0, 0, None))(
        state.agents, state.goal, params
    )
    model = GNNActorCritic(k=k, rounds=1)
    nn_params = model.init(jax.random.PRNGKey(0), obs)
    mean, log_std, value = model.apply(nn_params, obs)
    assert mean.shape == (1, n, 2)
    assert value.shape == (1, n)

    # With rounds=1, agent i's action depends only on {i} U knn(i): perturb
    # the obs row of an agent outside agent 0's neighborhood.
    _, _, idx = parse_knn_obs(obs, k)
    neighborhood = set(np.asarray(idx[0, 0]).tolist()) | {0}
    outsider = next(j for j in range(n) if j not in neighborhood)
    # Ensure agent 0 is also not in the outsider's... irrelevant: messages
    # flow from gathered rows only, so row-perturbation is sufficient.
    perturbed = obs.at[0, outsider, :2].add(0.25)
    mean2, _, value2 = model.apply(nn_params, perturbed)
    np.testing.assert_allclose(
        np.asarray(mean[0, 0]), np.asarray(mean2[0, 0]), rtol=1e-6
    )
    # The centralized critic DOES see the perturbation.
    assert abs(float(value2[0, 0] - value[0, 0])) > 1e-7


@pytest.mark.slow
def test_gnn_mask_blocks_padded_neighbors():
    k, n = 2, 6
    obs_dim = EnvParams(num_agents=n, obs_mode="knn", knn_k=k).obs_dim
    obs = jax.random.normal(jax.random.PRNGKey(4), (2, n, obs_dim))
    # Force the index block to point everyone at agents 4 and 5.
    obs = obs.at[..., -k:].set(jnp.array([4.0, 5.0]))
    mask = jnp.ones((2, n)).at[:, 4:].set(0.0)
    model = GNNActorCritic(k=k, rounds=2)
    nn_params = model.init(jax.random.PRNGKey(0), obs)
    _, _, value = model.apply(nn_params, obs, mask)
    assert (np.asarray(value[:, 4:]) == 0.0).all()
    # Padded agents' embeddings must not leak through messages: perturbing
    # agent 4's obs row changes nothing for active agents.
    perturbed = obs.at[:, 4, :2].add(3.0)
    mean1, _, v1 = model.apply(nn_params, obs, mask)
    mean2, _, v2 = model.apply(nn_params, perturbed, mask)
    np.testing.assert_allclose(
        np.asarray(mean1[:, :4]), np.asarray(mean2[:, :4]), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(v1[:, :4]), np.asarray(v2[:, :4]), rtol=1e-6
    )


def test_gather_nodes():
    h = jnp.arange(12, dtype=jnp.float32).reshape(1, 4, 3)
    idx = jnp.array([[[1, 2], [0, 3], [3, 0], [2, 1]]])
    out = gather_nodes(h, idx)
    assert out.shape == (1, 4, 2, 3)
    np.testing.assert_array_equal(
        np.asarray(out[0, 0]), np.asarray(h[0, jnp.array([1, 2])])
    )


@pytest.mark.slow
def test_trainer_gnn_smoke():
    env_params = EnvParams(num_agents=16, obs_mode="knn", knn_k=4)
    model = GNNActorCritic(k=4, rounds=2)
    trainer = Trainer(
        env_params,
        ppo=PPOConfig(n_steps=4, n_epochs=2, batch_size=64),
        config=TrainConfig(num_formations=2, checkpoint=False),
        model=model,
    )
    assert trainer.per_formation
    metrics = trainer.run_iteration()
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["reward"]))


# ---------------------------------------------------------------------------
# knn under SPMD sharding (round-1 ADVICE high finding): "auto" must never
# hand a dp-sharded batch to the Pallas kernel under plain jit, and the
# shard_map-wrapped dp step must run the kernel on local blocks correctly.
# ---------------------------------------------------------------------------


def test_spmd_detection_contexts():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from marl_distributedformation_tpu.ops.knn import (
        _spmd_partitioner_controlled as ctl,
    )
    from marl_distributedformation_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 8})
    x = jnp.zeros((16, 8, 2))
    x_dp = jax.device_put(x, NamedSharding(mesh, P("dp")))
    assert not ctl(x)
    assert ctl(x_dp)
    seen = []
    jax.jit(lambda y: seen.append(ctl(y)) or y)(x_dp)
    assert seen[-1], "tracer under jit+mesh must report partitioner control"
    from marl_distributedformation_tpu.jax_compat import shard_map

    jax.jit(
        shard_map(
            lambda y: seen.append(ctl(y)) or y,
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        )
    )(x_dp)
    assert not seen[-1], "inside shard_map the kernel sees a local block"


def test_knn_batch_auto_on_sharded_input_runs():
    """impl='auto' on a dp-sharded batch under jit must compile and match
    the unsharded XLA result (it silently falls back to xla)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from marl_distributedformation_tpu.ops import knn_batch
    from marl_distributedformation_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 8})
    pts = jax.random.uniform(jax.random.PRNGKey(0), (16, 12, 2)) * 100
    pts_dp = jax.device_put(pts, NamedSharding(mesh, P("dp")))
    idx_ref, off_ref, d_ref = knn_batch(pts, 3, impl="xla")
    f = jax.jit(lambda p: knn_batch(p, 3, impl="auto"))
    idx, off, d = f(pts_dp)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(idx_ref))
    np.testing.assert_allclose(  # eager vs jit fuse sqrt differently
        np.asarray(d), np.asarray(d_ref), rtol=1e-4, atol=1e-4
    )


@pytest.mark.slow
def test_dp_step_shard_map_runs_kernel_on_local_blocks(tmp_path):
    """Trainer with a dp mesh + knn obs uses the shard_map-wrapped env step;
    forcing the (interpret-mode) Pallas kernel inside it must reproduce the
    unsharded XLA trainer's trajectory and update."""
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.parallel import make_shard_fn
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    def mk(sub, impl, shard_fn):
        return Trainer(
            EnvParams(
                num_agents=8, obs_mode="knn", knn_k=2, knn_impl=impl
            ),
            ppo=PPOConfig(n_steps=2, batch_size=16, n_epochs=1),
            config=TrainConfig(
                num_formations=8, seed=0, checkpoint=False,
                name="knn-dp", log_dir=str(tmp_path / sub),
            ),
            shard_fn=shard_fn,
        )

    t_ref = mk("ref", "xla", None)
    t_dp = mk("dp", "pallas_interpret", make_shard_fn({"dp": 8}))
    assert t_dp._env_step_fn is not None, "knn+mesh must use make_dp_step"
    for _ in range(2):
        m_ref = t_ref.run_iteration()
        m_dp = t_dp.run_iteration()
        np.testing.assert_allclose(
            float(m_ref["reward"]), float(m_dp["reward"]), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(t_ref.env_state.agents),
            np.asarray(t_dp.env_state.agents),
            rtol=1e-4, atol=1e-3,
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(t_ref.train_state.params),
        jax.tree_util.tree_leaves(t_dp.train_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        )
