"""Sebulba lane contract (tier-1): the split acting/learning
architecture (train/sebulba, docs/sebulba.md).

The acceptance pins from the sebulba ISSUE:

- depth-1 lockstep Sebulba is BITWISE-identical to the Anakin host loop
  at the same seed/config — params AND per-iteration metrics — on a
  clean config; a ramped-severity scenario run keeps the env trajectory
  bitwise while reward-derived metrics sit within ~1 ulp (Anakin's
  single program fuses intermediates Sebulba materializes at the
  rollout/update program boundary — docs/sebulba.md, parity modes);
- each slice program compiles exactly once (budget-1 receipts on
  ``actor_guard`` / ``learner_guard``) and the base class's Anakin
  program NEVER compiles (its RetraceGuard stays 0);
- Anakin's dispatch surfaces and Anakin-only constructor options are
  fenced off with actionable errors;
- pipelined ``train()`` checkpoints at chunk boundaries and a fresh
  driver on the same log_dir resumes the counters exactly;
- the continuous-falsifier lane attacks the live checkpoint stream and
  its ``from_falsifiers`` feedback schedule lands through
  ``request_scenario_schedule`` with ZERO train-program recompiles;
- the three chaos seams degrade instead of corrupting: an enqueue drop
  is a seq GAP (never a duplicate), a dequeue redelivery is absorbed by
  the seq guard (no trajectory consumed twice), a dropped publish keeps
  actors on the previous params version (latest wins, versions never
  regress).
"""

import jax
import numpy as np
import pytest

# Bitwise PRNG-stream comparisons need partitionable threefry forced
# before any key math (see PR 3's note in CHANGES.md).
from marl_distributedformation_tpu import jax_compat  # noqa: F401
from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.chaos import (
    FaultPlane,
    FaultSchedule,
    FaultSpec,
    check_no_duplicate_consume,
    check_params_version_monotone,
    set_fault_plane,
)
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.scenarios import (
    AdversaryConfig,
    ContinuousAdversary,
    ScenarioSchedule,
    ScenarioStage,
    from_falsifiers,
)
from marl_distributedformation_tpu.train import TrainConfig, Trainer
from marl_distributedformation_tpu.train.sebulba import (
    ParamBus,
    SebulbaDriver,
    TransferQueue,
)
from marl_distributedformation_tpu.utils import latest_checkpoint

PPO = PPOConfig(n_steps=4, batch_size=24, n_epochs=2)
ENV = EnvParams(num_agents=3, max_steps=20)


@pytest.fixture
def plane():
    """A test-private FaultPlane installed as the process-global one;
    the shipped default (disabled) is restored afterwards."""
    fresh = FaultPlane(enabled=True)
    previous = set_fault_plane(fresh)
    yield fresh
    set_fault_plane(previous)


def _config(tmp_path, **overrides):
    defaults = dict(
        num_formations=4,
        checkpoint=False,
        seed=0,
        name="sebulba",
        log_dir=str(tmp_path / "logs"),
        log_interval=1,
    )
    defaults.update(overrides)
    return TrainConfig(**defaults)


def make_anakin(tmp_path, scenario=None, **overrides):
    return Trainer(
        ENV,
        ppo=PPO,
        config=_config(tmp_path, name="anakin", **overrides),
        scenario_schedule=scenario,
    )


def make_sebulba(tmp_path, scenario=None, **overrides):
    return SebulbaDriver(
        ENV,
        ppo=PPO,
        config=_config(tmp_path, architecture="sebulba", **overrides),
        scenario_schedule=scenario,
    )


def two_stage_schedule():
    """Severity ramp + scenario-mix change (the fused-scan tests' shape)."""
    return ScenarioSchedule(
        stages=(
            ScenarioStage(rollouts=2, scenarios=("wind",), severity=0.8),
            ScenarioStage(
                rollouts=2, scenarios=("wind", "sensor_noise"), severity=0.3
            ),
        )
    )


def clean_schedule():
    """The scenarios=['clean'] seam reservation (trainer.py's spelling)."""
    return ScenarioSchedule(
        stages=(
            ScenarioStage(
                rollouts=1,
                scenarios=("clean",),
                severity=0.0,
                severity_start=0.0,
            ),
        )
    )


def _param_leaves(trainer):
    return [
        np.asarray(leaf)
        for leaf in jax.tree_util.tree_leaves(
            jax.device_get(trainer.train_state.params)
        )
    ]


# ---------------------------------------------------------------------------
# Lockstep parity: Sebulba == Anakin (the acceptance pin)
# ---------------------------------------------------------------------------


def test_lockstep_bitwise_matches_anakin_host_loop(tmp_path):
    """Depth-1 lockstep drives the REAL transfer plumbing (queue seq
    stamps, bus versions) yet reproduces Anakin's host loop bit for bit:
    same key threading, same op sequence, cut across two programs."""
    anakin = make_anakin(tmp_path / "anakin")
    sebulba = make_sebulba(tmp_path / "sebulba")
    for i in range(3):
        a = jax.device_get(anakin.run_iteration())
        s = jax.device_get(sebulba.run_lockstep_iteration())
        assert set(a) == set(s)
        for name in a:
            np.testing.assert_array_equal(
                np.asarray(s[name]),
                np.asarray(a[name]),
                err_msg=f"metric {name!r} diverges at iteration {i}",
            )
    assert anakin.num_timesteps == sebulba.num_timesteps
    for a, s in zip(_param_leaves(anakin), _param_leaves(sebulba)):
        np.testing.assert_array_equal(a, s)
    # The plumbing really ran: three enqueues, three consumes, three
    # publishes past the initial version 0.
    assert list(sebulba.transfer_queue.consumed_seqs) == [0, 1, 2]
    assert sebulba.param_bus.version == 3
    assert sebulba.consumed_versions == [0, 1, 2]


def test_lockstep_scenario_run_first_rollout_bitwise_rest_tight(tmp_path):
    """Ramped-severity scenario parity: the FIRST rollout (identical
    initial params) keeps the env trajectory bitwise — the rollout
    program is the same computation — and divergence enters only
    through the first update's reward-derived path (~1 ulp: Anakin's
    single fused program keeps intermediates Sebulba materializes at
    its program boundary). From iteration 2 on that ulp rides the
    params into actions, so the whole run — env trajectory, metrics,
    params — is pinned at tight tolerance instead (docs/sebulba.md,
    parity modes)."""
    anakin = make_anakin(tmp_path / "anakin", scenario=two_stage_schedule())
    sebulba = make_sebulba(
        tmp_path / "sebulba", scenario=two_stage_schedule()
    )
    for i in range(4):
        a = jax.device_get(anakin.run_iteration())
        s = jax.device_get(sebulba.run_lockstep_iteration())
        env_cmp = (
            np.testing.assert_array_equal
            if i == 0
            else lambda x, y, err_msg="": np.testing.assert_allclose(
                x, y, rtol=1e-4, atol=1e-4, err_msg=err_msg
            )
        )
        for ea, es in zip(
            jax.tree_util.tree_leaves(jax.device_get(anakin.env_state)),
            jax.tree_util.tree_leaves(jax.device_get(sebulba.env_state)),
        ):
            env_cmp(
                np.asarray(ea),
                np.asarray(es),
                err_msg=f"env trajectory diverges at iteration {i}",
            )
        env_cmp(
            np.asarray(jax.device_get(anakin.obs)),
            np.asarray(jax.device_get(sebulba.obs)),
        )
        for name in a:
            np.testing.assert_allclose(
                np.asarray(s[name]),
                np.asarray(a[name]),
                rtol=1e-4,
                atol=1e-5,
                err_msg=f"metric {name!r} diverges at iteration {i}",
            )
    assert anakin._scenario_rollouts == sebulba._scenario_rollouts == 4
    for a, s in zip(_param_leaves(anakin), _param_leaves(sebulba)):
        np.testing.assert_allclose(a, s, rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# Budget-1 receipts per slice; Anakin surfaces fenced off
# ---------------------------------------------------------------------------


def test_each_slice_program_compiles_exactly_once(tmp_path):
    sebulba = make_sebulba(tmp_path)
    for _ in range(4):
        sebulba.run_lockstep_iteration()
    assert sebulba.actor_guard.count == 1
    assert sebulba.learner_guard.count == 1
    # The base class's fused Anakin program was never dispatched.
    assert sebulba.retrace_guard.count == 0


def test_anakin_dispatch_surfaces_and_options_are_fenced(tmp_path):
    sebulba = make_sebulba(tmp_path)
    with pytest.raises(SystemExit, match="run_lockstep_iteration"):
        sebulba.run_iteration()
    with pytest.raises(SystemExit, match="drain width"):
        sebulba.run_chunk()
    with pytest.raises(SystemExit, match="recovery"):
        make_sebulba(tmp_path / "rec", recovery=True)
    with pytest.raises(SystemExit, match="iters_per_dispatch"):
        make_sebulba(tmp_path / "ipd", iters_per_dispatch=2)


# ---------------------------------------------------------------------------
# Pipelined train(): checkpoint at chunk boundaries, exact resume
# ---------------------------------------------------------------------------


def test_pipelined_train_checkpoints_and_resumes_exactly(tmp_path):
    per_iter = PPO.n_steps * 4 * ENV.num_agents  # n_steps * M * agents
    first = make_sebulba(
        tmp_path,
        checkpoint=True,
        save_freq=8,
        fused_chunk=2,
        total_timesteps=6 * per_iter,
    )
    record = first.train()
    assert record, "pipelined train produced no metrics record"
    assert first.num_timesteps >= 6 * per_iter
    assert latest_checkpoint(first.log_dir) is not None
    # Chunked consume: every consumed seq strictly increasing, every
    # consumed params version monotone (the campaign invariants hold on
    # a clean run too).
    assert not check_no_duplicate_consume(
        list(first.transfer_queue.consumed_seqs)
    )
    assert not check_params_version_monotone(first.consumed_versions)
    assert first.actor_guard.count == 1
    assert first.learner_guard.count == 1

    resumed = make_sebulba(
        tmp_path,
        checkpoint=True,
        resume=True,
        save_freq=8,
        fused_chunk=2,
        total_timesteps=6 * per_iter,
    )
    assert resumed.num_timesteps == first.num_timesteps
    for a, b in zip(_param_leaves(first), _param_leaves(resumed)):
        np.testing.assert_array_equal(a, b)
    # The resumed driver's bus serves the RESUMED params as version 0.
    version, params = resumed.param_bus.latest()
    assert version == 0
    before = resumed.num_timesteps
    assert resumed.run_lockstep_iteration()
    assert resumed.num_timesteps == before + per_iter


# ---------------------------------------------------------------------------
# Continuous falsifier lane -> curriculum feedback, zero recompiles
# ---------------------------------------------------------------------------


def test_continuous_adversary_feeds_schedule_with_zero_recompiles(tmp_path):
    """The train -> falsify -> train loop against a live sebulba run:
    the lane attacks the newest checkpoint, pushes a ``from_falsifiers``
    stage through ``request_scenario_schedule``, and the next actor
    dispatch trains the new mix WITHOUT recompiling either slice
    (severity and knobs are traced inputs; the spec-union sampler is the
    only thing rebuilt)."""
    sebulba = make_sebulba(tmp_path, scenario=clean_schedule())
    sebulba.run_lockstep_iteration()
    sebulba.run_lockstep_iteration()
    assert sebulba.actor_guard.count == 1
    assert sebulba.save() is not None

    pushed = []

    def on_schedule(schedule):
        pushed.append(schedule)
        sebulba.request_scenario_schedule(schedule)

    lane = ContinuousAdversary(
        sebulba.log_dir,
        ENV,
        config=AdversaryConfig(
            scenarios=("wind",),
            grid=3,
            generations=3,
            num_formations=4,
            drop_tolerance=0.02,
            resolution=0.001,
        ),
        on_schedule=on_schedule,
        feedback_rollouts=4,
    )
    report = lane.poll_once()
    assert report is not None, "the lane missed the live checkpoint"
    assert not lane.errors
    assert report["falsifiers"], (
        "an untrained policy must break under wind"
    )
    assert pushed, "falsifiers found but no feedback schedule pushed"
    assert lane.summary()["adversary_schedules_pushed"] == 1
    # Nothing re-attacked until a NEWER checkpoint lands.
    assert lane.poll_once() is None

    # Not applied yet: the training thread owns schedule state.
    assert sebulba._scenario_schedule.names == ("clean",)
    sebulba.run_lockstep_iteration()
    assert "adv:wind" in sebulba._scenario_schedule.names
    sebulba.run_lockstep_iteration()
    assert sebulba.actor_guard.count == 1, (
        "a curriculum swap must never recompile the actor program"
    )
    assert sebulba.learner_guard.count == 1, (
        "a curriculum swap must never recompile the learner program"
    )


def test_schedule_feedback_without_scenario_seam_fails_fast(tmp_path):
    sebulba = make_sebulba(tmp_path)
    with pytest.raises(ValueError, match="scenarios=\\['clean'\\]"):
        sebulba.request_scenario_schedule(
            from_falsifiers(
                [{"scenario": "wind", "severity": 0.5}], rollouts=2
            )
        )


# ---------------------------------------------------------------------------
# Chaos seams: drop / duplicate / stale degrade, never corrupt
# ---------------------------------------------------------------------------


def test_enqueue_drop_is_a_seq_gap_never_a_duplicate(plane):
    queue = TransferQueue(depth=2)
    plane.arm(FaultSchedule([FaultSpec("sebulba.enqueue", "raise", 1)]))
    assert queue.put({"x": 1}, params_version=0) is None
    assert queue.dropped_total == 1
    assert queue.put({"x": 2}, params_version=0) == 1  # seq 0 was spent
    item = queue.get(timeout_s=1.0)
    assert item.seq == 1
    assert list(queue.consumed_seqs) == [1]
    # A gap is fine; a duplicate would be a violation.
    assert not check_no_duplicate_consume(list(queue.consumed_seqs))


def test_dequeue_redelivery_absorbed_by_seq_guard(plane):
    queue = TransferQueue(depth=4)
    plane.arm(FaultSchedule([FaultSpec("sebulba.dequeue", "raise", 1)]))
    queue.put({"x": 1}, params_version=0)
    queue.put({"x": 2}, params_version=0)
    first = queue.get(timeout_s=1.0)  # delivered AND re-queued at head
    assert first.seq == 0
    second = queue.get(timeout_s=1.0)  # replay absorbed, next delivered
    assert second.seq == 1
    assert queue.duplicates_absorbed == 1
    assert list(queue.consumed_seqs) == [0, 1]
    assert not check_no_duplicate_consume(list(queue.consumed_seqs))


def test_dropped_publish_keeps_previous_version_latest_wins(plane):
    # Arm before ANY publish: the seam's hit counter ticks whenever the
    # plane is enabled, armed or not.
    plane.arm(
        FaultSchedule([FaultSpec("sebulba.param_publish", "raise", 2)])
    )
    bus = ParamBus()
    assert bus.publish({"w": 0.0}, 0)  # hit 1: clean
    assert not bus.publish({"w": 1.0}, 1)  # hit 2: dropped
    assert bus.publishes_dropped == 1
    version, params = bus.latest()
    assert version == 0 and params == {"w": 0.0}
    assert bus.publish({"w": 2.0}, 2)  # next version lands
    assert bus.version == 2
    # Latest wins: a regressed version can never take the slot.
    assert not bus.publish({"w": 1.0}, 1)
    assert bus.version == 2
    assert not check_params_version_monotone(bus.versions_published)


def test_lockstep_enqueue_drop_is_a_skipped_update(plane, tmp_path):
    """Under an armed drop the rollout happened but nothing was learned:
    lockstep returns an empty dict, the timestep counter advances by the
    ROLLOUT, and the next iteration learns normally off the next seq."""
    sebulba = make_sebulba(tmp_path)
    per_iter = PPO.n_steps * 4 * ENV.num_agents
    plane.arm(FaultSchedule([FaultSpec("sebulba.enqueue", "raise", 1)]))
    assert sebulba.run_lockstep_iteration() == {}
    assert sebulba.num_timesteps == per_iter
    assert sebulba.transfer_queue.dropped_total == 1
    assert sebulba.consumed_versions == []
    metrics = sebulba.run_lockstep_iteration()
    assert metrics
    assert list(sebulba.transfer_queue.consumed_seqs) == [1]
    assert sebulba.consumed_versions == [0]
