"""jax_compat shim contract: shard_map resolves and runs on the
installed JAX, and keeps resolving under either API generation (the
drift that broke 3 tier-1 tests at 5 call sites — ISSUE 1 satellite)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from marl_distributedformation_tpu import jax_compat
from marl_distributedformation_tpu.parallel import make_mesh


def test_resolves_on_installed_jax():
    impl, is_new = jax_compat.resolve_shard_map()
    assert callable(impl)
    assert is_new == hasattr(jax, "shard_map")


@pytest.mark.parametrize("check_vma", [None, False])
def test_shard_map_executes_on_installed_jax(check_vma):
    mesh = make_mesh({"dp": 8})
    f = jax_compat.shard_map(
        lambda x: x * 2,
        mesh=mesh,
        in_specs=P("dp"),
        out_specs=P("dp"),
        check_vma=check_vma,
    )
    x = jnp.arange(16.0)
    np.testing.assert_array_equal(np.asarray(jax.jit(f)(x)), np.asarray(x) * 2)


def test_new_api_spelling_resolves(monkeypatch):
    """A monkeypatched ``jax.shard_map`` (the new-API spelling) must win
    and receive ``check_vma`` untranslated."""
    seen = {}

    def fake_shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        seen.update(
            mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
        return f

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    out = jax_compat.shard_map(
        abs, mesh="m", in_specs="i", out_specs="o", check_vma=False
    )
    assert out is abs
    assert seen == {
        "mesh": "m", "in_specs": "i", "out_specs": "o", "check_vma": False,
    }


def test_old_api_spelling_resolves(monkeypatch):
    """With no ``jax.shard_map`` (the installed 0.4.x reality, forced
    here for both generations), the experimental module resolves and
    ``check_vma`` translates to ``check_rep``."""
    # graftlint: disable=deprecated-api — monkeypatching the legacy module
    import jax.experimental.shard_map as legacy_mod

    monkeypatch.delattr(jax, "shard_map", raising=False)
    seen = {}

    def fake_legacy(f, *, mesh, in_specs, out_specs, check_rep=True):
        seen.update(check_rep=check_rep)
        return f

    monkeypatch.setattr(legacy_mod, "shard_map", fake_legacy)
    out = jax_compat.shard_map(
        abs, mesh="m", in_specs="i", out_specs="o", check_vma=False
    )
    assert out is abs
    assert seen == {"check_rep": False}


def test_check_vma_none_leaves_default(monkeypatch):
    """check_vma=None must not forward ANY checker kwarg — the installed
    default stays in charge on both API generations."""
    def fake_shard_map(f, *, mesh, in_specs, out_specs, **kw):
        assert not kw, f"unexpected kwargs {kw}"
        return f

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    assert (
        jax_compat.shard_map(abs, mesh="m", in_specs="i", out_specs="o")
        is abs
    )


def test_manual_axis_context_detection():
    """The legacy-JAX trace probe: False eagerly and under plain jit,
    True inside shard_map — the boundary _spmd_partitioner_controlled
    needs when avals carry no sharding."""
    if hasattr(jax, "shard_map"):
        pytest.skip(
            "sharding-in-types JAX: detection uses aval.sharding, the "
            "axis-env probe is legacy-only"
        )
    assert not jax_compat.manual_axis_context()
    seen = []
    mesh = make_mesh({"dp": 8})

    def probe(x):
        seen.append(jax_compat.manual_axis_context())
        return x

    jax.jit(probe)(jnp.zeros((8,)))
    assert seen[-1] is False
    jax.jit(
        jax_compat.shard_map(
            probe, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
        )
    )(jnp.zeros((8,)))
    assert seen[-1] is True
