"""Always-learning pipeline contract (tier-1, multi-device CPU).

The acceptance pins from the pipeline ISSUE:

- incremental checkpoint discovery preserves the classic contract
  (step-order yield, torn ``.tmp`` files invisible, ``latest`` ==
  ``latest_checkpoint``) while idle polls skip the directory listing;
- the gate's verdict logic rejects non-finite / clean-regressed /
  rung-regressed candidates and bootstraps cleanly (pure-function unit
  tests — no eval needed);
- ``promotions.jsonl`` lines carry the versioned schema;
- the rollback monitor needs a sustained breach, not one noisy sample;
- ``reload_pinned(monotonic=False)`` is a real coordinated demotion;
- END TO END on the conftest 8-device CPU mesh: a trainer's checkpoint
  series with one sabotaged (NaN params) candidate — the sabotaged step
  is provably never served, passing candidates serve step-monotonically,
  a forced serving-metric regression rolls the fleet back to last-good,
  and the gate's eval program compiles EXACTLY once across every
  candidate (budget-1 receipt in the verdict log).
"""

import json
import math
import os

import jax
import numpy as np
import pytest
from flax import serialization

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.pipeline import (
    AlwaysLearningPipeline,
    CheckpointStream,
    GateConfig,
    PromotionLog,
    RollbackMonitor,
    judge_candidate,
)
from marl_distributedformation_tpu.pipeline.promote import PROMOTIONS_SCHEMA
from marl_distributedformation_tpu.serving.fleet import (
    fleet_from_checkpoint_dir,
    warmup_fleet,
)
from marl_distributedformation_tpu.train import TrainConfig, Trainer
from marl_distributedformation_tpu.utils.checkpoint import (
    CheckpointDiscovery,
    _write_atomic,
    checkpoint_path,
    checkpoint_step,
    latest_checkpoint,
)

ENV = EnvParams(num_agents=3, max_steps=20)


@pytest.fixture
def private_tracer(tmp_path):
    """A test-private obs tracer with a flight recorder, installed as
    the process-global one for the duration of the test (the pipeline's
    seams resolve get_tracer() at call time)."""
    from marl_distributedformation_tpu.obs import (
        FlightRecorder,
        Tracer,
        set_tracer,
    )

    tracer = Tracer(
        ring_size=4096,
        flightrec=FlightRecorder(tmp_path / "flightrec", last_n=256),
    )
    previous = set_tracer(tracer)
    yield tracer
    set_tracer(previous)


@pytest.fixture
def private_registry():
    """A test-private MetricsRegistry installed as the process-global
    one (the pipeline's seams resolve get_registry() at call time)."""
    from marl_distributedformation_tpu.obs import (
        MetricsRegistry,
        set_registry,
    )

    registry = MetricsRegistry()
    previous = set_registry(registry)
    yield registry
    set_registry(previous)


@pytest.fixture
def private_ledger():
    """A test-private ProgramLedger installed as the process-global one
    (every ledgered compile seam resolves get_ledger() at call time)."""
    from marl_distributedformation_tpu.obs import ProgramLedger, set_ledger

    ledger = ProgramLedger(enabled=True)
    previous = set_ledger(ledger)
    yield ledger
    set_ledger(previous)


# ---------------------------------------------------------------------------
# Incremental discovery (utils.checkpoint.CheckpointDiscovery)
# ---------------------------------------------------------------------------


def _touch_ckpt(log_dir, step):
    path = checkpoint_path(log_dir, step)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_bytes(b"x")
    return path


def test_discovery_order_and_torn_write_invisibility(tmp_path):
    """Same contract as latest_checkpoint: step order regardless of
    creation order, dot-prefixed .tmp files never observed."""
    for step in (5, 30, 10):  # scrambled creation order
        _touch_ckpt(tmp_path, step)
    (tmp_path / ".rl_model_999_steps.msgpack.tmp").write_bytes(b"torn")
    (tmp_path / "notes.txt").write_text("not a checkpoint")
    disco = CheckpointDiscovery(tmp_path)
    assert [checkpoint_step(p) for p in disco.poll_new()] == [5, 10, 30]
    assert disco.latest() == latest_checkpoint(tmp_path)
    # New higher step appears incrementally…
    _touch_ckpt(tmp_path, 40)
    assert [checkpoint_step(p) for p in disco.poll_new()] == [40]
    # …while a LOWER step landing later is ignored by the consuming
    # stream (never-go-backward) and by latest().
    _touch_ckpt(tmp_path, 20)
    assert disco.poll_new() == []
    assert checkpoint_step(disco.latest()) == 40


def test_discovery_idle_polls_skip_listing(tmp_path, monkeypatch):
    """Steady-state polls of an unchanged directory must be one stat —
    no O(total checkpoints) re-list/re-parse (the always-learning
    degradation this path exists to avoid)."""
    _touch_ckpt(tmp_path, 10)
    monkeypatch.setattr(CheckpointDiscovery, "_MTIME_SLACK_S", 0.0)
    calls = []
    real_scandir = os.scandir

    def counting_scandir(path):
        calls.append(str(path))
        return real_scandir(path)

    monkeypatch.setattr(os, "scandir", counting_scandir)
    disco = CheckpointDiscovery(tmp_path)
    assert [checkpoint_step(p) for p in disco.poll_new()] == [10]
    listed = len(calls)
    assert listed >= 1
    for _ in range(5):  # idle polls: no listing
        assert disco.poll_new() == []
        assert checkpoint_step(disco.latest()) == 10
    assert len(calls) == listed
    # A new checkpoint bumps the dir mtime -> exactly the next poll
    # re-lists and finds it.
    _touch_ckpt(tmp_path, 20)
    assert [checkpoint_step(p) for p in disco.poll_new()] == [20]
    assert len(calls) > listed


def test_discovery_latest_survives_retraction(tmp_path):
    """Rollback deletes promoted checkpoints: latest() must step back
    down to the surviving newest file instead of returning a ghost."""
    _touch_ckpt(tmp_path, 10)
    p20 = _touch_ckpt(tmp_path, 20)
    disco = CheckpointDiscovery(tmp_path)
    assert checkpoint_step(disco.latest()) == 20
    p20.unlink()
    assert checkpoint_step(disco.latest()) == 10


def test_stream_yields_each_checkpoint_once(tmp_path):
    stream = CheckpointStream(tmp_path, poll_interval_s=0.01)
    assert stream.wait(0.05) == []
    _touch_ckpt(tmp_path, 7)
    stream.nudge()
    got = stream.wait(5.0)
    assert [checkpoint_step(p) for p in got] == [7]
    assert stream.poll() == []


# ---------------------------------------------------------------------------
# Gate verdict logic (pure) + promotions.jsonl schema
# ---------------------------------------------------------------------------

METRIC = "episode_return_per_agent"


def _cells(value):
    return {"wind": {"1": {METRIC: value}}}


def test_judge_bootstrap_and_pass():
    # No baseline: any finite candidate bootstraps.
    assert judge_candidate(
        METRIC, {METRIC: 100.0}, _cells(50.0), None, None, 0.05, 0.10
    ) == []
    # Matching-or-better candidate passes against a baseline.
    assert judge_candidate(
        METRIC, {METRIC: 101.0}, _cells(55.0),
        {METRIC: 100.0}, _cells(50.0), 0.05, 0.10,
    ) == []


def test_judge_rejects_clean_regression():
    reasons = judge_candidate(
        METRIC, {METRIC: 80.0}, _cells(50.0),
        {METRIC: 100.0}, _cells(50.0), 0.05, 0.10,
    )
    assert len(reasons) == 1 and "clean" in reasons[0]


def test_judge_rejects_severity_rung_regression():
    reasons = judge_candidate(
        METRIC, {METRIC: 100.0}, _cells(30.0),
        {METRIC: 100.0}, _cells(50.0), 0.05, 0.10,
    )
    assert len(reasons) == 1 and "severity rung wind@1" in reasons[0]


def test_judge_rejects_non_finite_even_at_bootstrap():
    reasons = judge_candidate(
        METRIC, {METRIC: math.nan}, _cells(50.0), None, None, 0.05, 0.10
    )
    assert len(reasons) == 1 and "non-finite" in reasons[0]
    # NaN in a rung cell is caught too, and short-circuits.
    reasons = judge_candidate(
        METRIC, {METRIC: 10.0}, _cells(math.inf),
        {METRIC: 100.0}, _cells(50.0), 0.05, 0.10,
    )
    assert len(reasons) == 1 and "non-finite" in reasons[0]


def test_judge_missing_baseline_cell_is_not_a_regression():
    assert judge_candidate(
        METRIC, {METRIC: 100.0},
        {"storm": {"1": {METRIC: 1.0}}},  # baseline never saw storm
        {METRIC: 100.0}, _cells(50.0), 0.05, 0.10,
    ) == []


def test_promotion_log_schema(tmp_path):
    log = PromotionLog(tmp_path / "promotions.jsonl")
    log.append("rejected", step=10, checkpoint="x", reasons=["bad"])
    log.append("promoted", step=20, checkpoint="y", reasons=[])
    records = PromotionLog.read(tmp_path / "promotions.jsonl")
    assert [r["event"] for r in records] == ["rejected", "promoted"]
    for r in records:
        assert r["schema"] == PROMOTIONS_SCHEMA
        assert isinstance(r["time"], float)
        assert isinstance(r["step"], int)
        # Schema 5: every line carries the lane stamp — None for a
        # single-model pipeline like this one.
        assert r["model_id"] is None
    # Append-only JSONL: every line independently parseable.
    lines = (tmp_path / "promotions.jsonl").read_text().splitlines()
    assert all(json.loads(ln) for ln in lines)


def test_promotion_log_stamps_model_id(tmp_path):
    """A lane-keyed log (serving/tenancy) stamps its model_id on EVERY
    line, and the round trip preserves it verbatim."""
    path = tmp_path / "promotions.jsonl"
    log = PromotionLog(path, model_id="formation-a")
    log.append("promoted", step=10, checkpoint="x")
    log.append("rejected", step=20, checkpoint="y", reasons=["bad"])
    for rec in PromotionLog.read(path):
        assert rec["schema"] == PROMOTIONS_SCHEMA
        assert rec["model_id"] == "formation-a"
    # The raw lines carry the stamp too (the log is read by jq-grade
    # tooling, not only PromotionLog.read).
    for line in path.read_text().splitlines():
        assert json.loads(line)["model_id"] == "formation-a"


def test_promotion_log_reader_accepts_old_schemas_rejects_unknown(tmp_path):
    """Schema bumps 1 -> 2 (trace_id + spans) -> 3 (adversarial
    falsifiers) -> 4 (mesh host_count/commit_round) -> 5 (tenant
    model_id): old logs stay readable — the reader backfills the newer
    fields as None so schema-5 consumers need no per-line branching —
    and an UNKNOWN (future) schema fails loudly instead of being
    silently misread."""
    assert PROMOTIONS_SCHEMA == 5
    path = tmp_path / "promotions.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({  # a verbatim PR-7-era line
            "schema": 1, "event": "promoted", "time": 1.0, "step": 10,
            "checkpoint": "rl_model_10_steps.msgpack",
        }) + "\n")
        f.write(json.dumps({  # a verbatim obs-era (PR 8) line
            "schema": 2, "event": "promoted", "time": 2.0, "step": 20,
            "trace_id": "abc123", "spans": {"gate_eval_s": 0.5},
        }) + "\n")
    PromotionLog(path).append(
        "rejected", step=30, trace_id="def456",
        falsifiers=[{"scenario": "wind", "severity": 0.4}],
    )
    oldest, obs_era, new = PromotionLog.read(path)
    assert oldest["schema"] == 1
    assert oldest["trace_id"] is None and oldest["spans"] is None
    assert oldest["falsifiers"] is None
    assert obs_era["schema"] == 2
    assert obs_era["trace_id"] == "abc123"
    assert obs_era["spans"] == {"gate_eval_s": 0.5}
    assert obs_era["falsifiers"] is None
    assert new["schema"] == PROMOTIONS_SCHEMA
    assert new["trace_id"] == "def456"
    assert new["falsifiers"] == [{"scenario": "wind", "severity": 0.4}]
    # Every pre-4 line (and a schema-4 rejection, which never swaps)
    # lacks the mesh commit attribution — backfilled None everywhere.
    assert oldest["host_count"] is None and oldest["commit_round"] is None
    assert obs_era["host_count"] is None
    assert new["host_count"] is None and new["commit_round"] is None
    # Schema 5 backfill: pre-tenancy lines are the None lane.
    assert oldest["model_id"] is None and obs_era["model_id"] is None
    # A schema-4 line written with the adversarial rung OFF has no
    # falsifiers key either — the reader backfills None there too, so
    # consumers never branch per line (or KeyError) on gate config.
    PromotionLog(path).append(
        "promoted", step=40, trace_id="ghi789",
        host_count=2, commit_round=7,
    )
    assert PromotionLog.read(path)[-1]["falsifiers"] is None
    assert PromotionLog.read(path)[-1]["host_count"] == 2
    assert PromotionLog.read(path)[-1]["commit_round"] == 7
    with open(path, "a") as f:
        f.write(json.dumps({"schema": 99, "event": "promoted"}) + "\n")
    with pytest.raises(ValueError, match="schema 99"):
        PromotionLog.read(path)


# ---------------------------------------------------------------------------
# Rollback monitor
# ---------------------------------------------------------------------------


def test_rollback_monitor_ratio_needs_sustained_breach():
    values = {"latency_p95_ms": 10.0}
    monitor = RollbackMonitor(
        lambda: values, "latency_p95_ms", ratio=2.0,
        baseline_samples=2, trip_after=2,
    )
    assert not monitor.observe()  # baseline sample 1
    assert not monitor.observe()  # baseline sample 2 -> baseline 10
    assert monitor.baseline == 10.0
    values["latency_p95_ms"] = 50.0
    assert not monitor.observe()  # breach 1 of 2
    values["latency_p95_ms"] = 11.0
    assert not monitor.observe()  # recovered: streak resets
    values["latency_p95_ms"] = 50.0
    assert not monitor.observe()
    assert monitor.observe()  # sustained -> trip
    monitor.reset()
    assert monitor.baseline is None  # new serving normal


def test_rollback_monitor_ratio_negative_baseline():
    # Episode returns in this env are negative penalty sums; the ratio
    # limit must sit on the breach side of a negative baseline (a
    # multiplicative limit flips sides and trips on healthy samples).
    values = {"return": -10.0}
    monitor = RollbackMonitor(
        lambda: values, "return", ratio=1.5, direction="below",
        baseline_samples=1, trip_after=1,
    )
    assert not monitor.observe()  # baseline -10
    assert monitor.limit() == pytest.approx(-15.0)
    assert not monitor.observe()  # healthy: -10 is above the limit
    values["return"] = -14.0
    assert not monitor.observe()  # regressed but within the margin
    values["return"] = -16.0
    assert monitor.observe()  # past baseline - |baseline|*(ratio-1)


def test_rollback_monitor_absolute_threshold_and_direction():
    values = {"q": 5.0}
    below = RollbackMonitor(
        lambda: values, "q", threshold=1.0, direction="below", trip_after=1
    )
    assert not below.observe()
    values["q"] = 0.5
    assert below.observe()
    # Missing metric / failing sampler: skipped, never a trip.
    none = RollbackMonitor(
        lambda: {}, "missing", threshold=1.0, trip_after=1
    )
    assert not none.observe()
    with pytest.raises(ValueError):
        RollbackMonitor(lambda: values, "q")  # no limit configured
    with pytest.raises(ValueError):
        RollbackMonitor(lambda: values, "q", ratio=0.5)


# ---------------------------------------------------------------------------
# Coordinator pinned reload (the demotion hook)
# ---------------------------------------------------------------------------


def _train_checkpoints(log_dir, iterations=3, seed=0):
    """A tiny real training run: returns the checkpoint paths written."""
    per_iter = 4 * ENV.num_agents * 5
    trainer = Trainer(
        ENV,
        ppo=PPOConfig(n_steps=5, n_epochs=2, batch_size=32),
        config=TrainConfig(
            num_formations=4,
            total_timesteps=iterations * per_iter,
            save_freq=5,
            name="pipeline_test",
            log_dir=str(log_dir),
            seed=seed,
        ),
    )
    trainer.train()
    # Budget-1 receipts this run earned, for the ledger entry-count
    # pin in the e2e (the trainer object itself is discarded).
    _train_checkpoints.last_receipts = trainer.retrace_guard.count
    return sorted(
        log_dir.glob("rl_model_*_steps.msgpack"), key=checkpoint_step
    )


def _sabotage_nan(path):
    """Corrupt a checkpoint's params with NaN, keeping the architecture
    (it must LOAD fine and fail the gate on eval, not on restore)."""
    from marl_distributedformation_tpu.utils.checkpoint import (
        msgpack_restore_file,
    )

    raw = msgpack_restore_file(path)
    raw["params"] = jax.tree_util.tree_map(
        lambda x: np.full_like(x, np.nan)
        if isinstance(x, np.ndarray) and np.issubdtype(x.dtype, np.floating)
        else x,
        raw["params"],
    )
    # check_finite=False: production writers can no longer publish a
    # non-finite state (the train-lane write gate, docs/recovery.md) —
    # this fixture deliberately forges one to prove the GATE still
    # rejects it at eval time (defense in depth one layer up).
    _write_atomic(path, raw, check_finite=False)


def test_reload_pinned_demotes_backward(tmp_path):
    ckpts = _train_checkpoints(tmp_path, iterations=2)
    assert len(ckpts) >= 2
    router, coordinator = fleet_from_checkpoint_dir(
        tmp_path, env_params=ENV, act_dim=ENV.act_dim,
        num_replicas=2, buckets=(1, 8),
    )
    steps = [checkpoint_step(p) for p in ckpts]
    with router:
        warmup_fleet(router, (ENV.obs_dim,))
        assert coordinator.fleet_step == steps[-1]
        # Monotonic pinned reload refuses to go backward…
        assert not coordinator.reload_pinned(ckpts[0], monotonic=True)
        assert coordinator.fleet_step == steps[-1]
        # …the demotion hook does it, at the fleet batch barrier.
        assert coordinator.reload_pinned(ckpts[0], monotonic=False)
        assert coordinator.fleet_step == steps[0]
        obs = np.zeros((2, ENV.obs_dim), np.float32)
        res = router.submit(obs).result(timeout=30.0)
        assert res.model_step == steps[0]
        # Same-step pin is a no-op, not a swap.
        assert not coordinator.reload_pinned(ckpts[0], monotonic=False)


def test_deferred_promotion_and_failed_rollback(tmp_path, private_tracer):
    """A wedged replica aborts the batch-barrier commit: a passing
    candidate must be DEFERRED (never logged 'promoted', never the gate
    baseline) until the commit lands, and a tripped rollback whose
    pinned reload cannot commit must log 'rollback_failed' and keep the
    alarm armed for a retry — the audit log never claims a swap the
    fleet did not serve."""
    log_dir = tmp_path / "run"
    ckpts = _train_checkpoints(log_dir, iterations=2)
    s1, s2 = checkpoint_step(ckpts[0]), checkpoint_step(ckpts[-1])
    pipeline = AlwaysLearningPipeline(
        log_dir,
        ENV,
        gate_config=GateConfig(
            scenarios=("wind",), severities=(1.0,), eval_formations=8,
            clean_tolerance=10.0, rung_tolerance=10.0,
        ),
        poll_interval_s=0.01,
    )
    # Bootstrap consumes ONLY the first candidate; s2 stays queued.
    assert pipeline.wait_first_promotion(timeout_s=120.0)
    router, coordinator = fleet_from_checkpoint_dir(
        pipeline.promoted_dir, env_params=ENV, act_dim=ENV.act_dim,
        num_replicas=2, buckets=(1,),
    )
    coordinator.commit_timeout_s = 0.2
    with router:
        warmup_fleet(router, (ENV.obs_dim,))
        pipeline.attach_fleet(router, coordinator)
        served = {"v": 0.0}
        pipeline.attach_monitor(
            RollbackMonitor(lambda: served, "v", threshold=10.0,
                            trip_after=1)
        )
        wedged = router.replicas[1].registry.batch_lock
        wedged.acquire()  # a worker stuck inside a device dispatch
        try:
            pipeline.poll_once()  # s2 passes the gate, commit aborts
        finally:
            wedged.release()
        assert [r.step for r in pipeline.promotions] == [s1]
        assert pipeline.gate.baseline_step == s1
        assert coordinator.fleet_step == s1
        events = [
            r["event"] for r in PromotionLog.read(
                log_dir / "promotions.jsonl"
            )
        ]
        assert events.count("promotion_deferred") == 1
        assert events.count("promoted") == 1  # only s1
        # The wedged barrier was a postmortem-grade incident: the flight
        # recorder dumped the ring the moment the commit aborted, with
        # the deferred candidate's trace on the snapshot.
        wedge_dumps = [
            p
            for p in private_tracer.flightrec.dumps()
            if "wedged_barrier_abort" in p.name
        ]
        assert len(wedge_dumps) == 1
        payload = json.loads(wedge_dumps[0].read_text())
        assert payload["context"]["step"] == s2
        assert payload["trace_id"]
        # Barrier clear -> the next poll retries and the commit lands.
        pipeline.poll_once()
        assert [r.step for r in pipeline.promotions] == [s1, s2]
        assert pipeline.gate.baseline_step == s2
        assert coordinator.fleet_step == s2
        # Tripped rollback against a wedged fleet: the demotion cannot
        # commit — truthfully 'rollback_failed', state restored, alarm
        # still armed.
        served["v"] = 100.0
        wedged.acquire()
        try:
            pipeline.poll_once()
        finally:
            wedged.release()
        assert pipeline.rollbacks == []
        assert coordinator.fleet_step == s2
        events = [
            r["event"] for r in PromotionLog.read(
                log_dir / "promotions.jsonl"
            )
        ]
        assert events.count("rollback_failed") == 1
        # Cleared wedge + still-breaching metric -> the retry demotes.
        pipeline.poll_once()
        assert len(pipeline.rollbacks) == 1
        assert coordinator.fleet_step == s1
        assert pipeline.gate.baseline_step == s1


def test_leapfrogged_deferred_candidate_is_superseded_not_promoted(
    tmp_path,
):
    """Two candidates defer behind a wedged barrier; when it clears, the
    coordinator commits straight to the NEWEST — the older deferred
    candidate never served and must terminate as 'promotion_superseded'
    (never a baseline, never a rollback target), not be back-filled as
    'promoted'."""
    log_dir = tmp_path / "run"
    ckpts = _train_checkpoints(log_dir, iterations=3)
    steps = [checkpoint_step(p) for p in ckpts]
    s1, s2, s3 = steps[0], steps[1], steps[-1]
    pipeline = AlwaysLearningPipeline(
        log_dir,
        ENV,
        gate_config=GateConfig(
            scenarios=("wind",), severities=(1.0,), eval_formations=8,
            clean_tolerance=10.0, rung_tolerance=10.0,
        ),
        poll_interval_s=0.01,
    )
    assert pipeline.wait_first_promotion(timeout_s=120.0)
    router, coordinator = fleet_from_checkpoint_dir(
        pipeline.promoted_dir, env_params=ENV, act_dim=ENV.act_dim,
        num_replicas=2, buckets=(1,),
    )
    coordinator.commit_timeout_s = 0.2
    with router:
        warmup_fleet(router, (ENV.obs_dim,))
        pipeline.attach_fleet(router, coordinator)
        wedged = router.replicas[1].registry.batch_lock
        wedged.acquire()
        try:
            pipeline.poll_once()  # s2 AND s3 pass the gate, both defer
        finally:
            wedged.release()
        assert [r.step for r in pipeline.promotions] == [s1]
        assert len(pipeline._deferred) == 2
        pipeline.poll_once()  # retry: commit jumps straight to s3
        assert coordinator.fleet_step == s3
        assert [r.step for r in pipeline.promotions] == [s1, s3]
        assert pipeline.gate.baseline_step == s3
        assert pipeline._deferred == []
    records = PromotionLog.read(log_dir / "promotions.jsonl")
    superseded = [
        r for r in records if r["event"] == "promotion_superseded"
    ]
    assert [r["step"] for r in superseded] == [s2]
    promoted = [r for r in records if r["event"] == "promoted"]
    assert [r["step"] for r in promoted] == [s1, s3]


def test_gate_rejects_non_checkpoint_path(tmp_path):
    """evaluate() honors its never-raises contract even for a filename
    checkpoint_step cannot parse."""
    from marl_distributedformation_tpu.pipeline import PromotionGate

    gate = PromotionGate(ENV, GateConfig())
    weird = tmp_path / "rl_model_final.msgpack"
    weird.write_bytes(b"x")
    verdict = gate.evaluate(weird)
    assert not verdict.passed
    assert "not a checkpoint path" in verdict.reasons[0]


def test_gate_rebase_survives_evicted_history():
    """A demotion cascade longer than the bounded baseline history must
    degrade to bootstrap judging, not KeyError the control plane."""
    from marl_distributedformation_tpu.pipeline import (
        GateVerdict,
        PromotionGate,
    )

    gate = PromotionGate(ENV, GateConfig())
    for step in range(10, 110, 10):  # 10 promotions, history keeps 8
        gate.accept(
            GateVerdict(
                step=step, path=f"rl_model_{step}_steps.msgpack",
                passed=True, reasons=[], clean={METRIC: 1.0},
                cells=_cells(1.0), baseline_step=None,
                eval_compiles=1, eval_seconds=0.0,
            )
        )
    gate.rebase(10)  # long since evicted
    assert gate.baseline_step == 10
    # Bootstrap judging: a finite candidate passes, NaN still rejected.
    assert judge_candidate(
        METRIC, {METRIC: 5.0}, _cells(5.0),
        gate._baseline_clean, gate._baseline_cells, 0.05, 0.10,
    ) == []
    gate.rebase(100)  # still in history: full baseline restored
    assert gate._baseline_clean == {METRIC: 1.0}


# ---------------------------------------------------------------------------
# End to end: trainer -> gate -> fleet, sabotage + rollback
# ---------------------------------------------------------------------------


def test_pipeline_end_to_end(
    tmp_path, private_tracer, private_registry, private_ledger
):
    assert len(jax.local_devices()) >= 2  # the conftest mesh

    log_dir = tmp_path / "run"
    ckpts = _train_checkpoints(log_dir, iterations=3)
    assert len(ckpts) >= 3
    steps = [checkpoint_step(p) for p in ckpts]
    s1, s_bad, s3 = steps[0], steps[1], steps[-1]
    _sabotage_nan(ckpts[1])

    # Tolerances are wide: this run is 3 tiny PPO iterations, so honest
    # candidates wobble — the sabotage is caught by the FINITE check,
    # which no tolerance can launder.
    pipeline = AlwaysLearningPipeline(
        log_dir,
        ENV,
        gate_config=GateConfig(
            scenarios=("wind",),
            severities=(1.0,),
            eval_formations=8,
            clean_tolerance=10.0,
            rung_tolerance=10.0,
        ),
        poll_interval_s=0.01,
    )

    # Bootstrap: the first candidate passes and is published.
    assert pipeline.wait_first_promotion(timeout_s=120.0)
    assert pipeline.promotions[0].step == s1
    assert set(pipeline.promoter.published_steps()) == {s1}

    # Fleet boots from the PROMOTED directory only.
    router, coordinator = fleet_from_checkpoint_dir(
        pipeline.promoted_dir, env_params=ENV, act_dim=ENV.act_dim,
        num_replicas=2, buckets=(1, 8),
    )
    with router:
        warmup_fleet(router, (ENV.obs_dim,))
        # Watching the raw trainer dir is the vulnerability this
        # subsystem closes — refuse it loudly.
        with pytest.raises(ValueError):
            pipeline.attach_fleet(
                router,
                type(coordinator)(log_dir, router),
            )
        pipeline.attach_fleet(router, coordinator)
        served = {"v": 0.0}
        monitor = RollbackMonitor(
            lambda: served, "v", threshold=10.0, trip_after=1
        )
        pipeline.attach_monitor(monitor)

        def served_step():
            obs = np.zeros((2, ENV.obs_dim), np.float32)
            return router.submit(obs).result(timeout=30.0).model_step

        assert served_step() == s1

        # Drain the remaining candidates: the sabotaged one is rejected,
        # the rest promote in step order.
        while pipeline.poll_once():
            pass
        assert [v.step for v in pipeline.rejections] == [s_bad]
        assert "non-finite" in pipeline.rejections[0].reasons[0]
        assert [r.step for r in pipeline.promotions] == [
            s for s in steps if s != s_bad
        ]
        # The sabotaged step was never published, never served.
        assert s_bad not in pipeline.promoter.published_steps()
        assert coordinator.fleet_step == s3
        assert served_step() == s3
        # Promotion latency measured for every post-fleet promotion.
        assert all(
            r.latency_s is not None and r.latency_s >= 0.0
            for r in pipeline.promotions[1:]
        )

        # Forced serving-metric regression -> rollback to last-good.
        served["v"] = 100.0
        pipeline.poll_once()
        assert len(pipeline.rollbacks) == 1
        assert pipeline.rollbacks[0]["from_step"] == s3
        assert pipeline.rollbacks[0]["to_step"] == s1
        assert coordinator.fleet_step == s1
        assert served_step() == s1
        # Retraction: the demoted checkpoint left the promoted dir, so
        # the coordinator's next poll cannot re-promote it.
        assert set(pipeline.promoter.published_steps()) == {s1}
        assert not coordinator.refresh()
        assert coordinator.fleet_step == s1
        # The gate judges future candidates against what serves AGAIN.
        assert pipeline.gate.baseline_step == s1

    # THE compile-once receipt: one gate eval program across every
    # candidate — bootstrap, sabotage, promotions — and it is recorded
    # in the verdict log.
    assert pipeline.gate.program.compile_count == 1
    records = PromotionLog.read(log_dir / "promotions.jsonl")
    events = [r["event"] for r in records]
    assert events.count("promoted") == len(pipeline.promotions)
    assert events.count("rejected") == 1
    assert events.count("rolled_back") == 1
    for r in records:
        assert r["schema"] == PROMOTIONS_SCHEMA
        if r["event"] in ("promoted", "rejected"):
            assert r["gate_eval_compiles"] == 1
    rolled = [r for r in records if r["event"] == "rolled_back"][0]
    assert rolled["from_step"] == s3 and rolled["to_step"] == s1
    # Serving-side receipt: the swaps + demotion never recompiled.
    assert all(
        count <= 1
        for per in router.compile_counts().values()
        for count in per.values()
    )
    # Summary carries the bench fields.
    summary = pipeline.summary()
    assert summary["gate_eval_compiles"] == 1
    assert summary["promotions"] == len(pipeline.promotions)
    assert summary["rollbacks"] == 1
    assert summary["gate_eval_steps_per_sec"] > 0

    # --- The live-metrics plane (ISSUE 11): the pipeline lane recorded
    # its counters/gauges/histograms into the process registry, merged
    # with the fleet families (any FleetMetrics.snapshot reader — the
    # emit pacer, /v1/metrics, the rollback sampler — publishes them
    # there), so ONE Prometheus namespace carries the whole loop. ---
    router.snapshot()  # the sampling path: one read refreshes the gauges
    live = private_registry.snapshot()
    assert live["pipeline_promotions_total"] == float(summary["promotions"])
    assert live["pipeline_rejections_total"] == float(summary["rejections"])
    assert live["pipeline_rollbacks_total"] == 1.0
    assert live["pipeline_served_step"] == float(s1)  # post-rollback
    assert live["gate_eval_steps_per_sec"] > 0.0
    assert live["pipeline_gate_eval_seconds_count"] >= 3.0
    assert live["pipeline_gate_eval_seconds_p50"] > 0.0
    assert live["pipeline_stream_poll_lag_seconds"] >= 0.0
    assert live["promotion_latency_seconds_count"] >= 1.0
    # Fleet families folded into the same namespace by snapshot().
    assert live["fleet_routed_total"] >= 1.0
    assert "latency_p95_ms" in live
    # And the merged dict renders as parseable Prometheus text.
    from marl_distributedformation_tpu.obs import prometheus_exposition

    text = prometheus_exposition(live)
    assert "# TYPE marl_pipeline_promotions_total counter" in text
    assert "# TYPE marl_pipeline_gate_eval_seconds summary" in text

    # --- The program ledger (ISSUE 13 acceptance): every budget-1
    # compile site in the loop appears in the census EXACTLY once per
    # compilation — entry count equals the sum of the RetraceGuard
    # receipts (trainer dispatch program + gate MatrixProgram + every
    # serving rung on every replica), with all receipts still 1-per-
    # program with the ledger ON. ---
    entries = private_ledger.entries()
    receipts = (
        _train_checkpoints.last_receipts
        + pipeline.gate.program.guard.count
        + sum(
            c
            for per in router.compile_counts().values()
            for c in per.values()
        )
    )
    assert len(entries) == receipts
    assert all(rec.traces == 1 for rec in entries)
    subsystems = {rec.subsystem for rec in entries}
    assert {"trainer", "gate", "serving"} <= subsystems
    # Facts are present-or-explicitly-unavailable, never silently blank.
    from marl_distributedformation_tpu.obs.ledger import ANALYSIS_SOURCES

    for rec in entries:
        assert rec.analysis_source in ANALYSIS_SOURCES
        if rec.analysis_source == "unavailable":
            assert rec.analysis_error
    # The ledger families fold into the same exposition namespace.
    ledger_text = prometheus_exposition(
        {**live, **private_ledger.snapshot()}
    )
    assert "# TYPE marl_program_flops gauge" in ledger_text
    assert 'program="gate_robustness_matrix_eval"' in ledger_text

    # --- The obs spine (ISSUE 8 acceptance): ONE trace reconstructs a
    # promotion end to end, and its span decomposition sums to the
    # recorded promotion_latency_s within 10%. ---
    promoted_recs = [r for r in records if r["event"] == "promoted"]
    trace_ids = [r["trace_id"] for r in records if r["event"] in
                 ("promoted", "rejected")]
    assert all(trace_ids)
    assert len(set(trace_ids)) == len(trace_ids)  # one trace PER candidate
    post_fleet = [
        r for r in promoted_recs
        if r.get("promotion_latency_s") is not None
    ]
    assert post_fleet, "no promotion measured against a live fleet"
    for r in post_fleet:
        spans = r["spans"]
        for stage in (
            "stream_poll_s", "gate_eval_s", "publish_s",
            "barrier_commit_s", "first_serve_s",
        ):
            assert spans.get(stage, -1.0) >= 0.0, (stage, spans)
        total = sum(spans.values())
        latency = r["promotion_latency_s"]
        assert abs(total - latency) <= 0.1 * latency + 0.05, (
            f"span decomposition {total:.4f}s does not account for "
            f"promotion_latency_s {latency:.4f}s: {spans}"
        )
    # The rollback shares one trace across trip + demotion, and the trip
    # flight-dumped the ring for the postmortem.
    assert rolled["trace_id"]
    trip_dumps = [
        p
        for p in private_tracer.flightrec.dumps()
        if "rollback_trip" in p.name
    ]
    assert len(trip_dumps) == 1
    trip = json.loads(trip_dumps[0].read_text())
    assert trip["trace_id"] == rolled["trace_id"]
    assert trip["context"]["from_step"] == s3
    assert any(
        r.get("name") == "serve.batch" for r in trip["records"]
    ), "the flight dump lost the pre-trip serving history"
    # The summary aggregates the per-stage p50s bench phase 8 records.
    breakdown = summary["promotion_span_breakdown"]
    assert breakdown.get("gate_eval_s", 0.0) > 0.0
    assert breakdown.get("barrier_commit_s", -1.0) >= 0.0

    # And scripts/trace_report.py renders the run's spans into a valid
    # Chrome trace-event file, filterable to ONE promotion's trace.
    import sys as _sys
    from pathlib import Path as _Path

    dump = private_tracer.dump(tmp_path / "trace_spans.json")
    _sys.path.insert(
        0, str(_Path(__file__).resolve().parent.parent / "scripts")
    )
    try:
        import trace_report
    finally:
        _sys.path.pop(0)
    out = tmp_path / "promo.chrome.json"
    tid = post_fleet[-1]["trace_id"]
    assert trace_report.main(
        [str(dump), "--trace-id", tid, "--out", str(out)]
    ) == 0
    trace = json.loads(out.read_text())
    span_names = {
        e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"
    }
    assert {
        "promotion.stream_poll", "promotion.gate_eval",
        "gate.matrix_eval", "promotion.publish",
        "promotion.barrier_commit", "reload.commit",
        "promotion.first_serve",
    } <= span_names, span_names
    assert all(
        e["args"]["trace_id"] == tid
        for e in trace["traceEvents"]
        if e.get("ph") == "X"
    )
