"""Tests for GAE, PPO loss, and the minibatch update."""

import dataclasses

import chex
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.algo import (
    MinibatchData,
    PPOConfig,
    compute_gae,
    ppo_loss,
    ppo_update,
)
from marl_distributedformation_tpu.models import MLPActorCritic, distributions
from flax.training.train_state import TrainState


def naive_gae(rewards, values, dones, last_value, gamma, lam):
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    next_adv = np.zeros_like(last_value)
    for t in reversed(range(T)):
        next_v = values[t + 1] if t + 1 < T else last_value
        nt = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_v * nt - values[t]
        next_adv = delta + gamma * lam * nt * next_adv
        adv[t] = next_adv
    return adv, adv + values


def test_gae_matches_naive_loop():
    rng = np.random.default_rng(0)
    T, B = 12, 7
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = (rng.random((T, B)) < 0.2).astype(np.float32)
    last_value = rng.normal(size=(B,)).astype(np.float32)
    adv, ret = compute_gae(
        jnp.asarray(rewards),
        jnp.asarray(values),
        jnp.asarray(dones),
        jnp.asarray(last_value),
        0.99,
        0.95,
    )
    exp_adv, exp_ret = naive_gae(rewards, values, dones, last_value, 0.99, 0.95)
    np.testing.assert_allclose(np.asarray(adv), exp_adv, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret), exp_ret, rtol=1e-4, atol=1e-5)


def test_gae_no_bootstrap_through_done():
    """A done at t cuts both the value bootstrap and advantage recursion."""
    rewards = jnp.array([[1.0], [1.0], [1.0]])
    values = jnp.zeros((3, 1))
    dones = jnp.array([[0.0], [1.0], [0.0]])
    last_value = jnp.array([100.0])
    adv, _ = compute_gae(rewards, values, dones, last_value, 1.0, 1.0)
    # t=1 terminal: adv = r only. t=0 chains through t=1.
    np.testing.assert_allclose(np.asarray(adv[1]), [1.0])
    np.testing.assert_allclose(np.asarray(adv[0]), [2.0])
    # t=2 bootstraps from last_value (no done).
    np.testing.assert_allclose(np.asarray(adv[2]), [101.0])


def _make_train_state(seed=0, obs_dim=8):
    config = PPOConfig(batch_size=16, n_epochs=2)
    model = MLPActorCritic(act_dim=2)
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim)))
    ts = TrainState.create(
        apply_fn=model.apply, params=params, tx=config.make_optimizer()
    )
    return ts, config


def _make_batch(ts, key, n=64, obs_dim=8):
    k1, k2 = jax.random.split(key)
    obs = jax.random.normal(k1, (n, obs_dim))
    mean, log_std, values = ts.apply_fn(ts.params, obs)
    actions = distributions.sample(k2, mean, log_std)
    logp = distributions.log_prob(actions, mean, log_std)
    advantages = jax.random.normal(jax.random.PRNGKey(3), (n,))
    return MinibatchData(
        obs=obs,
        actions=actions,
        old_log_probs=logp,
        advantages=advantages,
        returns=values + advantages,
    )


def test_ppo_loss_at_old_policy():
    """With new == old policy, ratio == 1: policy loss is -mean(norm_adv)
    (~0 after normalization) and approx_kl is 0."""
    ts, config = _make_train_state()
    mb = _make_batch(ts, jax.random.PRNGKey(1))
    loss, metrics = ppo_loss(ts.params, ts.apply_fn, mb, config)
    assert np.isfinite(float(loss))
    np.testing.assert_allclose(float(metrics["approx_kl"]), 0.0, atol=1e-5)
    np.testing.assert_allclose(float(metrics["clip_fraction"]), 0.0, atol=1e-6)
    # Normalized advantages have ~zero mean -> tiny policy loss.
    assert abs(float(metrics["policy_loss"])) < 1e-5
    # Value loss is mse(returns, values) = mean(adv^2) here.
    np.testing.assert_allclose(
        float(metrics["value_loss"]),
        float((mb.advantages**2).mean()),
        rtol=1e-4,
    )


def test_ppo_loss_clipping_engages():
    ts, config = _make_train_state()
    mb = _make_batch(ts, jax.random.PRNGKey(2))
    # Shift old log probs to fake a big ratio.
    mb_shifted = MinibatchData(
        obs=mb.obs,
        actions=mb.actions,
        old_log_probs=mb.old_log_probs - 1.0,
        advantages=mb.advantages,
        returns=mb.returns,
    )
    _, metrics = ppo_loss(ts.params, ts.apply_fn, mb_shifted, config)
    assert float(metrics["clip_fraction"]) > 0.9


def test_value_clipping_semantics():
    """clip_range_vf (SB3's optional value clipping): None reproduces the
    unclipped loss exactly, a huge range is a no-op, and range 0 pins the
    value loss at MSE(returns, old_values) with ZERO critic gradient —
    old_values recovered from the GAE identity returns - advantages."""
    ts, config = _make_train_state()
    mb = _make_batch(ts, jax.random.PRNGKey(5))

    import dataclasses

    loss_none, m_none = ppo_loss(ts.params, ts.apply_fn, mb, config)
    loss_huge, _ = ppo_loss(
        ts.params, ts.apply_fn, mb,
        dataclasses.replace(config, clip_range_vf=1e9),
    )
    np.testing.assert_allclose(
        float(loss_none), float(loss_huge), rtol=1e-6
    )

    # Evaluate at PERTURBED params: the fixture builds returns from ts's
    # own values, so at ts the prediction sits exactly on the clip
    # boundary (values == old_values), where clip's subgradient passes
    # through — only away from the boundary does clipping bite.
    ts2, _ = _make_train_state(seed=1)
    cfg0 = dataclasses.replace(config, clip_range_vf=0.0)
    _, m0 = ppo_loss(ts2.params, ts2.apply_fn, mb, cfg0)
    old_values = np.asarray(mb.returns - mb.advantages)
    np.testing.assert_allclose(
        float(m0["value_loss"]),
        float(((np.asarray(mb.returns) - old_values) ** 2).mean()),
        rtol=1e-5,
    )
    grads = jax.grad(lambda p: ppo_loss(p, ts2.apply_fn, mb, cfg0)[0])(
        ts2.params
    )
    vf_grad = np.abs(
        np.asarray(grads["params"]["vf_head"]["kernel"])
    ).max()
    assert vf_grad == 0.0, f"critic grad must vanish at clip 0: {vf_grad}"

    # Mid-range: hand-computed clipped MSE.
    cfg_mid = dataclasses.replace(config, clip_range_vf=0.05)
    _, m_mid = ppo_loss(ts2.params, ts2.apply_fn, mb, cfg_mid)
    _, _, values = ts2.apply_fn(ts2.params, mb.obs)
    clipped = old_values + np.clip(
        np.asarray(values) - old_values, -0.05, 0.05
    )
    np.testing.assert_allclose(
        float(m_mid["value_loss"]),
        float(((np.asarray(mb.returns) - clipped) ** 2).mean()),
        rtol=1e-5,
    )


def test_ppo_update_improves_loss_and_changes_params():
    ts, config = _make_train_state()
    data = _make_batch(ts, jax.random.PRNGKey(4), n=256)
    ts2, metrics = ppo_update(ts, data, jax.random.PRNGKey(5), config)
    assert np.isfinite(float(metrics["loss"]))
    # Parameters moved.
    diff = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()), ts.params, ts2.params
    )
    assert max(jax.tree_util.tree_leaves(diff)) > 0
    # Value loss should drop when re-evaluated on the same data.
    _, m0 = ppo_loss(ts.params, ts.apply_fn, data, config)
    _, m1 = ppo_loss(ts2.params, ts.apply_fn, data, config)
    assert float(m1["value_loss"]) < float(m0["value_loss"])


def test_ent_coef_decay_matches_constant_when_degenerate():
    """ent_coef_final == ent_coef must be BIT-IDENTICAL to no schedule:
    the decay plumbing may not perturb unscheduled numerics."""
    ts, config = _make_train_state()
    data = _make_batch(ts, jax.random.PRNGKey(4), n=64)
    plain, m_plain = ppo_update(ts, data, jax.random.PRNGKey(5), config)
    degen = dataclasses.replace(
        config, ent_coef_final=config.ent_coef, total_iterations=3
    )
    sched, m_sched = ppo_update(ts, data, jax.random.PRNGKey(5), degen)
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.params),
        jax.tree_util.tree_leaves(sched.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert "ent_coef" not in m_plain
    np.testing.assert_allclose(float(m_sched["ent_coef"]), config.ent_coef)


def test_ent_coef_decay_anneals_with_optimizer_step():
    """The coefficient interpolates ent_coef -> ent_coef_final on
    TrainState.step: consecutive updates report strictly decreasing
    means, reaching ~ent_coef_final by the horizon."""
    ts, config = _make_train_state()
    config = dataclasses.replace(
        config, ent_coef_final=0.0, total_iterations=2
    )
    data = _make_batch(ts, jax.random.PRNGKey(4), n=64)
    ts, m1 = ppo_update(ts, data, jax.random.PRNGKey(5), config)
    ts, m2 = ppo_update(ts, data, jax.random.PRNGKey(6), config)
    ts, m3 = ppo_update(ts, data, jax.random.PRNGKey(7), config)
    c1, c2, c3 = (float(m["ent_coef"]) for m in (m1, m2, m3))
    assert config.ent_coef >= c1 > c2 > c3 >= 0.0
    # Past the horizon the schedule clamps at the final value.
    ts, m4 = ppo_update(ts, data, jax.random.PRNGKey(8), config)
    np.testing.assert_allclose(float(m4["ent_coef"]), 0.0, atol=1e-7)


def test_ent_coef_decay_requires_horizon():
    ts, config = _make_train_state()
    config = dataclasses.replace(config, ent_coef_final=0.0)
    data = _make_batch(ts, jax.random.PRNGKey(4), n=256)
    with pytest.raises(AssertionError, match="total_iterations"):
        ppo_update(ts, data, jax.random.PRNGKey(5), config)


def test_log_std_decay_projects_parameter_to_ceiling():
    """log_std_final clamps the LEARNED log_std parameter under a
    linearly-decaying ceiling: by the horizon the parameter itself sits
    at/below the final value — so the checkpointed policy IS the
    narrow-noise policy and deterministic eval stops misrepresenting
    it. (A loss-term pull could not do this: clipped-Adam steps are
    ~learning_rate-sized, far too slow to traverse nats in-run.)"""
    ts, config = _make_train_state()
    config = dataclasses.replace(
        config, log_std_final=-2.0, total_iterations=4
    )
    data = _make_batch(ts, jax.random.PRNGKey(4), n=64)
    start = float(np.asarray(ts.params["params"]["log_std"]).max())
    for k in range(8):
        ts, m = ppo_update(ts, data, jax.random.PRNGKey(10 + k), config)
    end = float(np.asarray(ts.params["params"]["log_std"]).max())
    assert start == 0.0  # parity init
    assert end <= -2.0 + 1e-6, f"log_std above final ceiling: {end}"
    # Past the horizon the ceiling clamps at the final value.
    np.testing.assert_allclose(
        float(m["log_std_ceiling"]), -2.0, atol=1e-6
    )
    # The entropy schedule was NOT engaged (independent knobs).
    assert "ent_coef" not in m


def test_log_std_decay_touches_only_log_std():
    """The projection is path-keyed: a single-minibatch update with the
    schedule must leave every non-log_std parameter BIT-IDENTICAL to the
    plain run (the schedule adds no loss term and no gradient), and clamp
    log_std to the ceiling."""
    ts, config = _make_train_state()
    config = dataclasses.replace(config, n_epochs=1, batch_size=256)
    data = _make_batch(ts, jax.random.PRNGKey(4), n=256)
    plain, _ = ppo_update(ts, data, jax.random.PRNGKey(5), config)
    sched_cfg = dataclasses.replace(
        config, log_std_final=-2.0, total_iterations=3
    )
    sched, m_sched = ppo_update(ts, data, jax.random.PRNGKey(5), sched_cfg)
    flat_plain = jax.tree_util.tree_flatten_with_path(plain.params)[0]
    flat_sched = jax.tree_util.tree_flatten_with_path(sched.params)[0]
    for (path, a), (_, b) in zip(flat_plain, flat_sched):
        name = getattr(path[-1], "key", None)
        if name == "log_std":
            np.testing.assert_array_equal(
                np.asarray(b),
                np.minimum(np.asarray(a), float(m_sched["log_std_ceiling"])),
            )
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_log_std_decay_requires_horizon():
    ts, config = _make_train_state()
    config = dataclasses.replace(config, log_std_final=-2.0)
    data = _make_batch(ts, jax.random.PRNGKey(4), n=256)
    with pytest.raises(AssertionError, match="total_iterations"):
        ppo_update(ts, data, jax.random.PRNGKey(5), config)


def test_ppo_update_batch_remainder_dropped():
    """total=100, batch=64 -> one minibatch of 64 per epoch, no crash."""
    ts, config = _make_train_state()
    config = PPOConfig(batch_size=64, n_epochs=1)
    data = _make_batch(ts, jax.random.PRNGKey(6), n=100)
    ts2, metrics = ppo_update(ts, data, jax.random.PRNGKey(7), config)
    assert np.isfinite(float(metrics["loss"]))
