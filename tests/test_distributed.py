"""Multi-host distributed primitives, exercised single-process.

True multi-host behavior (DCN collectives, per-host shards) can't run in a
single-process CI; these tests pin the single-process degradations — which
the multi-host paths are written to share — plus the pure factoring logic
and the process-local -> global array construction on the 8-virtual-device
CPU mesh (conftest.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.formation import reset_batch
from marl_distributedformation_tpu.parallel import (
    global_from_local,
    init_distributed,
    is_coordinator,
    local_formation_slice,
    make_hybrid_mesh,
    shard_batch,
)
from marl_distributedformation_tpu.utils import MetricsLogger, save_checkpoint


def test_init_distributed_single_process_noop():
    assert init_distributed() is False  # no coordinator configured
    assert is_coordinator()


def test_hybrid_mesh_falls_back_single_slice():
    mesh = make_hybrid_mesh({"dp": 4, "sp": 2})
    assert mesh.shape == {"dp": 4, "sp": 2}
    mesh2 = make_hybrid_mesh({"dp": -1})
    assert mesh2.shape == {"dp": 8}


def test_local_formation_slice_single_process():
    start, count = local_formation_slice(4096)
    assert (start, count) == (0, 4096)
    # Explicit process_index computes any host's shard (here: as if 4 hosts
    # existed, host 3 of a 4096 split would start at 3072 — but with one
    # process the divisor is process_count, so the shard is the whole batch).
    start, count = local_formation_slice(64, process_index=0)
    assert (start, count) == (0, 64)


def test_global_from_local_matches_shard_batch():
    """Single-process, the process-local assembly must produce the same
    values and the same 'dp' placement as plain device_put sharding."""
    mesh = make_hybrid_mesh({"dp": 8})
    params = EnvParams(num_agents=5)
    state = reset_batch(jax.random.PRNGKey(0), params, 16)

    via_local = global_from_local(state, mesh)
    via_put = shard_batch(state, mesh)

    for a, b in zip(
        jax.tree_util.tree_leaves(via_local),
        jax.tree_util.tree_leaves(via_put),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding.is_equivalent_to(
            NamedSharding(mesh, P("dp")), a.ndim
        )


def test_global_from_local_usable_in_jit():
    mesh = make_hybrid_mesh({"dp": 8})
    local = jnp.arange(32, dtype=jnp.float32).reshape(16, 2)
    g = global_from_local(local, mesh)
    out = jax.jit(lambda x: (x * 2).sum())(g)
    assert float(out) == float(local.sum() * 2)


def test_partial_restore_across_checkpoint_layouts(tmp_path):
    """A learner-only (multi-host-style) checkpoint restores into a
    full single-host template — env keys simply stay fresh — and extra
    keys in the file are ignored."""
    from marl_distributedformation_tpu.utils import (
        restore_checkpoint_partial,
        save_checkpoint,
    )

    learner_only = {"params": {"w": jnp.ones((2, 2))}, "num_timesteps": 40}
    path = save_checkpoint(tmp_path, 40, learner_only)
    full_template = {
        "params": {"w": jnp.zeros((2, 2))},
        "num_timesteps": 0,
        "env_state": jnp.zeros((3,)),
    }
    restored = restore_checkpoint_partial(path, full_template)
    assert set(restored) == {"params", "num_timesteps"}
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]), 1.0)

    # Reverse: full checkpoint into a learner-only template.
    full = dict(full_template, extra=jnp.ones((1,)))
    path2 = save_checkpoint(tmp_path, 41, full)
    restored2 = restore_checkpoint_partial(
        path2, {"params": {"w": jnp.ones((2, 2))}, "num_timesteps": 7}
    )
    assert set(restored2) == {"params", "num_timesteps"}
    assert int(restored2["num_timesteps"]) == 0


def test_coordinator_guards_are_noops_single_process(tmp_path):
    """save_checkpoint writes and MetricsLogger emits on the coordinator
    (which a single process always is)."""
    path = save_checkpoint(tmp_path, 7, {"x": jnp.ones((2,))})
    assert path.exists()
    logger = MetricsLogger(tmp_path, use_wandb=False)
    logger.log({"reward": 1.0}, step=7)
    logger.close()
    assert (tmp_path / "metrics.jsonl").read_text().strip() != ""


def test_hetero_reset_batch_sharded_matches_unsharded():
    """Single-process degradation: the per-host-shard hetero reset equals
    the plain hetero_reset_batch (same keys, same counts), globally
    'dp'-sharded (round-1 ADVICE: HeteroTrainer multi-host start_stage)."""
    from marl_distributedformation_tpu.env.hetero import hetero_reset_batch
    from marl_distributedformation_tpu.parallel import (
        hetero_reset_batch_sharded,
        make_mesh,
    )

    params = EnvParams(num_agents=6, num_obstacles=2)
    n_agents = jnp.asarray([3, 6, 4, 2, 6, 5, 3, 4], jnp.int32)
    n_obstacles = jnp.asarray([0, 2, 1, 0, 2, 1, 0, 2], jnp.int32)
    key = jax.random.PRNGKey(7)
    mesh = make_mesh({"dp": 8})

    ref = hetero_reset_batch(key, params, n_agents, n_obstacles)
    sharded = hetero_reset_batch_sharded(
        key, params, n_agents, n_obstacles, mesh
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(ref),
        jax.tree_util.tree_leaves(sharded),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert not sharded.agents.sharding.is_fully_replicated


def test_init_distributed_cluster_marker_fallback(monkeypatch):
    """A cluster env marker without a reachable coordinator must degrade to
    single-process (with a warning), not crash."""
    import marl_distributedformation_tpu.parallel.distributed as dist

    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setenv("SLURM_JOB_NUM_NODES", "2")
    # jax.distributed.initialize will raise (no real Slurm env) — wrapped.
    assert dist.init_distributed() is False
    assert dist._initialized


def test_save_checkpoint_returns_path_single_process(tmp_path):
    path = save_checkpoint(tmp_path, 42, {"x": jnp.zeros((2,))})
    assert path is not None and path.exists()
