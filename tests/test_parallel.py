"""Mesh-sharding tests on the 8-virtual-device CPU mesh (conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.parallel import make_mesh, make_shard_fn
from marl_distributedformation_tpu.train import TrainConfig, Trainer


def test_virtual_device_count():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"


def test_make_mesh_shapes():
    mesh = make_mesh({"dp": 8})
    assert mesh.shape == {"dp": 8}
    mesh2 = make_mesh({"dp": 4, "sp": 2})
    assert mesh2.shape == {"dp": 4, "sp": 2}
    mesh3 = make_mesh({"dp": -1})
    assert mesh3.shape == {"dp": 8}
    with pytest.raises(ValueError):
        make_mesh({"dp": 16})


def _trainer(tmp_path, shard_fn=None, num_formations=8):
    return Trainer(
        EnvParams(num_agents=3),
        ppo=PPOConfig(n_steps=4, batch_size=24, n_epochs=2),
        config=TrainConfig(
            num_formations=num_formations,
            seed=0,
            checkpoint=False,
            name="mesh",
            log_dir=str(tmp_path / "logs"),
        ),
        shard_fn=shard_fn,
    )


def test_sharded_training_matches_single_device(tmp_path):
    """dp-sharded training is numerically the same program: metrics and
    updated params must match the unsharded run to fp32 tolerance."""
    t_single = _trainer(tmp_path / "single")
    t_sharded = _trainer(tmp_path / "sharded", shard_fn=make_shard_fn({"dp": 8}))

    for _ in range(2):
        m_single = t_single.run_iteration()
        m_sharded = t_sharded.run_iteration()
        np.testing.assert_allclose(
            float(m_single["reward"]), float(m_sharded["reward"]), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(m_single["loss"]), float(m_sharded["loss"]), rtol=1e-3
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(t_single.train_state.params),
        jax.tree_util.tree_leaves(t_sharded.train_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )


def test_sharded_env_state_placement(tmp_path):
    shard_fn = make_shard_fn({"dp": 8})
    trainer = _trainer(tmp_path, shard_fn=shard_fn, num_formations=16)
    sharding = trainer.env_state.agents.sharding
    assert sharding.is_equivalent_to(
        jax.sharding.NamedSharding(
            shard_fn.mesh, jax.sharding.PartitionSpec("dp")
        ),
        trainer.env_state.agents.ndim,
    )
    # Sharding survives a training iteration (no silent gather to one device).
    trainer.run_iteration()
    assert not trainer.env_state.agents.sharding.is_fully_replicated


def test_indivisible_formations_rejected(tmp_path):
    with pytest.raises(ValueError, match="not divisible"):
        _trainer(tmp_path, shard_fn=make_shard_fn({"dp": 8}), num_formations=12)
