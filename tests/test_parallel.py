"""Mesh-sharding tests on the 8-virtual-device CPU mesh (conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.parallel import make_mesh, make_shard_fn
from marl_distributedformation_tpu.train import TrainConfig, Trainer


def test_virtual_device_count():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"


def test_make_mesh_shapes():
    mesh = make_mesh({"dp": 8})
    assert mesh.shape == {"dp": 8}
    mesh2 = make_mesh({"dp": 4, "sp": 2})
    assert mesh2.shape == {"dp": 4, "sp": 2}
    mesh3 = make_mesh({"dp": -1})
    assert mesh3.shape == {"dp": 8}
    with pytest.raises(ValueError):
        make_mesh({"dp": 16})


def _trainer(tmp_path, shard_fn=None, num_formations=8):
    return Trainer(
        EnvParams(num_agents=3),
        ppo=PPOConfig(n_steps=4, batch_size=24, n_epochs=2),
        config=TrainConfig(
            num_formations=num_formations,
            seed=0,
            checkpoint=False,
            name="mesh",
            log_dir=str(tmp_path / "logs"),
        ),
        shard_fn=shard_fn,
    )


@pytest.mark.slow
def test_sharded_training_matches_single_device(tmp_path):
    """dp-sharded training is numerically the same program: metrics and
    updated params must match the unsharded run to fp32 tolerance."""
    t_single = _trainer(tmp_path / "single")
    t_sharded = _trainer(tmp_path / "sharded", shard_fn=make_shard_fn({"dp": 8}))

    for _ in range(2):
        m_single = t_single.run_iteration()
        m_sharded = t_sharded.run_iteration()
        np.testing.assert_allclose(
            float(m_single["reward"]), float(m_sharded["reward"]), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(m_single["loss"]), float(m_sharded["loss"]), rtol=1e-3
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(t_single.train_state.params),
        jax.tree_util.tree_leaves(t_sharded.train_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )


def test_sharded_env_state_placement(tmp_path):
    shard_fn = make_shard_fn({"dp": 8})
    trainer = _trainer(tmp_path, shard_fn=shard_fn, num_formations=16)
    sharding = trainer.env_state.agents.sharding
    assert sharding.is_equivalent_to(
        jax.sharding.NamedSharding(
            shard_fn.mesh, jax.sharding.PartitionSpec("dp")
        ),
        trainer.env_state.agents.ndim,
    )
    # Sharding survives a training iteration (no silent gather to one device).
    trainer.run_iteration()
    assert not trainer.env_state.agents.sharding.is_fully_replicated


def test_indivisible_formations_rejected(tmp_path):
    with pytest.raises(ValueError, match="not divisible"):
        _trainer(tmp_path, shard_fn=make_shard_fn({"dp": 8}), num_formations=12)


# ---------------------------------------------------------------------------
# Ring halo exchange: agent-axis ('sp') sharding (parallel/ring.py)
# ---------------------------------------------------------------------------

from marl_distributedformation_tpu.env.formation import reset_batch, step_batch
from marl_distributedformation_tpu.parallel import make_ring_step, place_ring_state


@pytest.mark.parametrize("dp,sp", [(1, 8), (2, 4), (4, 2), (8, 1)])
@pytest.mark.slow
def test_ring_step_matches_unsharded(dp, sp):
    """Agent-axis sharding is semantics-free: ring-step trajectories equal
    the unsharded vmap step exactly (same reset draws, same rewards/obs)."""
    params = EnvParams(num_agents=8, max_steps=3)  # resets inside the run
    M = 4 * dp if dp > 1 else 4
    mesh = make_mesh({"dp": dp, "sp": sp})
    ring_step = make_ring_step(params, mesh)

    state_ref = reset_batch(jax.random.PRNGKey(0), params, M)
    state_ring = place_ring_state(state_ref, mesh)

    rng = np.random.default_rng(1)
    for t in range(8):  # crosses the strict-parity reset at step 5
        vel = jnp.asarray(
            rng.uniform(-10, 10, (M, 8, 2)).astype(np.float32)
        )
        state_ref, tr_ref = step_batch(state_ref, vel, params)
        state_ring, tr_ring = ring_step(state_ring, vel)
        np.testing.assert_allclose(
            np.asarray(tr_ring.obs), np.asarray(tr_ref.obs),
            rtol=1e-5, atol=1e-6, err_msg=f"obs t={t}",
        )
        np.testing.assert_allclose(
            np.asarray(tr_ring.reward), np.asarray(tr_ref.reward),
            rtol=1e-4, atol=1e-4, err_msg=f"reward t={t}",
        )
        np.testing.assert_array_equal(
            np.asarray(tr_ring.done), np.asarray(tr_ref.done)
        )
        np.testing.assert_allclose(
            np.asarray(state_ring.agents), np.asarray(state_ref.agents),
            rtol=1e-5, atol=1e-5,
        )
        for k in tr_ref.metrics:
            np.testing.assert_allclose(
                np.asarray(tr_ring.metrics[k]),
                np.asarray(tr_ref.metrics[k]),
                rtol=1e-4, atol=1e-4, err_msg=f"metric {k} t={t}",
            )


def test_ring_step_sharding_layout():
    params = EnvParams(num_agents=8)
    mesh = make_mesh({"dp": 2, "sp": 4})
    ring_step = make_ring_step(params, mesh)
    state = place_ring_state(
        reset_batch(jax.random.PRNGKey(0), params, 4), mesh
    )
    vel = jnp.zeros((4, 8, 2))
    state2, tr = ring_step(state, vel)
    # Agent axis stays sharded over 'sp' after the step.
    assert not state2.agents.sharding.is_fully_replicated
    spec = state2.agents.sharding.spec
    assert tuple(spec)[:2] == ("dp", "sp")


def test_ring_step_rejects_indivisible_agents():
    mesh = make_mesh({"dp": 2, "sp": 4})
    with pytest.raises(ValueError, match="not divisible"):
        make_ring_step(EnvParams(num_agents=6), mesh)


# ---------------------------------------------------------------------------
# 'sp' sharding wired end-to-end through the Trainer (VERDICT.md round-1 #2)
# ---------------------------------------------------------------------------


def _sp_trainer(tmp_path, shard_fn=None):
    return Trainer(
        EnvParams(num_agents=8),
        ppo=PPOConfig(n_steps=4, batch_size=32, n_epochs=2),
        config=TrainConfig(
            num_formations=4,
            seed=0,
            checkpoint=False,
            name="sp",
            log_dir=str(tmp_path / "logs"),
        ),
        shard_fn=shard_fn,
    )


@pytest.mark.slow
def test_sp_sharded_training_matches_single_device(tmp_path):
    """Full train iterations on a {dp:2, sp:2} mesh: the halo-exchange env
    step + sharded PPO update must reproduce the unsharded trajectory (env
    states equal, params equal to fp32 reduction tolerance)."""
    t_single = _sp_trainer(tmp_path / "single")
    t_sp = _sp_trainer(
        tmp_path / "sp", shard_fn=make_shard_fn({"dp": 2, "sp": 2})
    )
    assert t_sp._env_step_fn is not None, "sp mesh must select the ring step"

    for i in range(2):
        m_single = t_single.run_iteration()
        m_sp = t_sp.run_iteration()
        np.testing.assert_allclose(
            float(m_single["reward"]), float(m_sp["reward"]),
            rtol=1e-4, err_msg=f"iter {i}",
        )
        np.testing.assert_allclose(
            float(m_single["loss"]), float(m_sp["loss"]), rtol=1e-3
        )
        # Same env trajectory step for step (resets included).
        np.testing.assert_allclose(
            np.asarray(t_single.env_state.agents),
            np.asarray(t_sp.env_state.agents),
            rtol=1e-4, atol=1e-3,
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(t_single.train_state.params),
        jax.tree_util.tree_leaves(t_sp.train_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        )


def test_sp_shard_fn_layout(tmp_path):
    trainer = _sp_trainer(
        tmp_path, shard_fn=make_shard_fn({"dp": 2, "sp": 2})
    )
    spec = trainer.env_state.agents.sharding.spec
    assert tuple(spec)[:2] == ("dp", "sp")
    trainer.run_iteration()
    assert not trainer.env_state.agents.sharding.is_fully_replicated
    spec_after = trainer.env_state.agents.sharding.spec
    assert tuple(spec_after)[:2] == ("dp", "sp")


def test_sp_shard_fn_accepts_knn_obs(tmp_path):
    """Round 3: knn swarms shard on 'sp' too (all-gather + local-query
    search). The Trainer selects the sharded step and one iteration runs;
    an indivisible agent count is still rejected."""
    trainer = Trainer(
        EnvParams(num_agents=8, obs_mode="knn", knn_k=2, knn_impl="xla"),
        config=TrainConfig(
            num_formations=4, checkpoint=False,
            log_dir=str(tmp_path / "logs"),
        ),
        shard_fn=make_shard_fn({"dp": 2, "sp": 2}),
    )
    assert trainer._env_step_fn is not None
    assert np.isfinite(trainer.run_iteration()["loss"])
    with pytest.raises(ValueError, match="divisible"):
        Trainer(
            EnvParams(num_agents=7, obs_mode="knn", knn_k=2),
            config=TrainConfig(
                num_formations=4, checkpoint=False,
                log_dir=str(tmp_path / "logs2"),
            ),
            shard_fn=make_shard_fn({"dp": 2, "sp": 2}),
        )


# ---------------------------------------------------------------------------
# Agent-axis sharding of knn swarms: all-gather + local-query search
# ---------------------------------------------------------------------------


def test_knn_local_matches_full_search():
    """knn_local on a slab returns exactly the corresponding rows of the
    full search (global indices, same tie-breaks — both use the identical
    distance expression and column order)."""
    from marl_distributedformation_tpu.ops import knn, knn_local

    pts = jnp.asarray(
        np.random.default_rng(3).uniform(0, 400, (12, 2)), jnp.float32
    )
    idx_full, off_full, d_full = knn(pts, 3)
    for offset, nq in ((0, 4), (4, 4), (8, 4), (3, 6)):
        idx, off, d = knn_local(pts[offset : offset + nq], pts, 3, offset)
        np.testing.assert_array_equal(
            np.asarray(idx), np.asarray(idx_full[offset : offset + nq])
        )
        np.testing.assert_allclose(
            np.asarray(off), np.asarray(off_full[offset : offset + nq]),
            rtol=1e-6, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(d), np.asarray(d_full[offset : offset + nq]),
            rtol=1e-6, atol=1e-6,
        )


@pytest.mark.parametrize("dp,sp", [(2, 4), (1, 8)])
@pytest.mark.slow
def test_knn_ring_step_matches_unsharded(dp, sp):
    """The sp-sharded knn swarm step (all-gather positions + knn_local per
    slab + halo-exchange reward mixing) reproduces the unsharded
    trajectory exactly — including the global neighbor indices carried in
    the observations."""
    params = EnvParams(
        num_agents=16, max_steps=3, obs_mode="knn", knn_k=3,
        knn_impl="xla",
    )
    M = 4 * dp if dp > 1 else 4
    mesh = make_mesh({"dp": dp, "sp": sp})
    ring_step = make_ring_step(params, mesh)

    state_ref = reset_batch(jax.random.PRNGKey(7), params, M)
    state_ring = place_ring_state(state_ref, mesh)

    rng = np.random.default_rng(11)
    for t in range(8):  # crosses the strict-parity auto-reset
        vel = jnp.asarray(
            rng.uniform(-10, 10, (M, 16, 2)).astype(np.float32)
        )
        state_ref, tr_ref = step_batch(state_ref, vel, params)
        state_ring, tr_ring = ring_step(state_ring, vel)
        np.testing.assert_allclose(
            np.asarray(tr_ring.obs), np.asarray(tr_ref.obs),
            rtol=1e-5, atol=1e-6, err_msg=f"obs t={t}",
        )
        np.testing.assert_allclose(
            np.asarray(tr_ring.reward), np.asarray(tr_ref.reward),
            rtol=1e-4, atol=1e-4, err_msg=f"reward t={t}",
        )
        np.testing.assert_array_equal(
            np.asarray(tr_ring.done), np.asarray(tr_ref.done)
        )
        np.testing.assert_allclose(
            np.asarray(state_ring.agents), np.asarray(state_ref.agents),
            rtol=1e-5, atol=1e-5,
        )


@pytest.mark.slow
def test_gnn_trains_on_sp_mesh(tmp_path):
    """A formation-level model (GNN) composes with agent-axis sharding:
    the env step runs the sharded all-gather + local-query search, and the
    SPMD partitioner re-gathers the agent axis where the per-formation
    forward needs it. One full iteration, finite loss."""
    from marl_distributedformation_tpu.models import GNNActorCritic

    params = EnvParams(num_agents=8, obs_mode="knn", knn_k=2, knn_impl="xla")
    trainer = Trainer(
        params,
        ppo=PPOConfig(n_steps=2, batch_size=64, n_epochs=1),
        config=TrainConfig(
            num_formations=4, checkpoint=False,
            log_dir=str(tmp_path / "logs"),
        ),
        model=GNNActorCritic(k=2, act_dim=2, goal_in_obs=params.goal_in_obs),
        shard_fn=make_shard_fn({"dp": 2, "sp": 2}),
    )
    assert trainer._env_step_fn is not None
    assert np.isfinite(trainer.run_iteration()["loss"])


@pytest.mark.slow
def test_weak_scaling_script_smoke(tmp_path, monkeypatch):
    """scripts/weak_scaling.py end-to-end at tiny sizes: every phase
    emits a row per device count and the doc table is written."""
    import json
    import os
    import subprocess
    import sys as _sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(
        os.environ,
        WS_DEVICES="1,2",
        WS_M_TOTAL="8",
        WS_M_TRAIN="8",
        WS_M_MEMBER="4",
        WS_ENV_CHUNK="4",
        WS_MIN_TIMED_S="0.1",
        WS_DOC=str(tmp_path / "weak_scaling.md"),
    )
    out = subprocess.run(
        [_sys.executable, str(repo / "scripts" / "weak_scaling.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
        cwd=repo,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rows = json.loads(out.stdout)
    got = {(r["phase"], r["devices"]) for r in rows}
    assert got == {
        (p, d) for p in ("dp_env", "dp_train", "sweep") for d in (1, 2)
    }
    assert all(r["steps_per_sec"] > 0 for r in rows)
    doc = (tmp_path / "weak_scaling.md").read_text()
    assert "| 2 |" in doc and "sweep" in doc
