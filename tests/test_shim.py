"""Contract tests for the reference-verbatim entry shim (vectorized_env.py).

The migration guide claims ``python vectorized_env.py name=x`` and
``FormationEnv(cfg)`` work unchanged (reference README.md:18,
vectorized_env.py:17); these pin that claim the way test_cli_dispatch pins
train.py's.
"""

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import train as train_cli
import vectorized_env as shim
from marl_distributedformation_tpu.compat.vec_env import FormationVecEnv
from marl_distributedformation_tpu.utils import load_config


def test_shim_forwards_to_train_main():
    assert shim.main is train_cli.main


def test_shim_import_is_light():
    """Importing the shim for FormationEnv must not pull the training
    stack (the lazy-main contract)."""
    import subprocess

    code = (
        "import vectorized_env, sys; "
        "assert 'train' not in sys.modules, 'train imported eagerly'; "
        "assert 'marl_distributedformation_tpu.algo' not in sys.modules"
    )
    subprocess.run(
        [sys.executable, "-c", code],
        check=True,
        cwd=Path(__file__).resolve().parent.parent,
    )


def test_reference_signature_formation_env_constructs_and_steps():
    cfg = load_config(["name=shimtest", "num_formation=4", "platform=cpu"])
    env = shim.FormationEnv(cfg)
    assert isinstance(env, FormationVecEnv)
    assert env.num_envs == 4 * cfg.num_agents_per_formation
    obs = env.reset()
    obs2, rewards, dones, infos = env.step(np.zeros((env.num_envs, 2)))
    assert obs.shape == obs2.shape == (env.num_envs, obs.shape[1])
    assert rewards.shape == dones.shape == (env.num_envs,)
    assert len(infos) == env.num_envs


def test_shim_trains_and_snapshots_config(tmp_path, monkeypatch):
    """The documented verbatim command trains end-to-end and leaves the
    hydra-snapshot analog (config.json); a resume does not clobber it."""
    monkeypatch.setattr(train_cli, "repo_root", lambda: tmp_path)
    args = [
        "name=shimrun", "platform=cpu", "num_formation=4",
        "num_agents_per_formation=3", "total_timesteps=120", "n_steps=10",
        "save_freq=10", "use_wandb=false",
    ]
    shim.main(args)
    run_dir = tmp_path / "logs" / "shimrun"
    assert (run_dir / "config.json").exists()
    assert list(run_dir.glob("rl_model_*_steps.msgpack"))
    before = (run_dir / "config.json").read_text()
    shim.main(args + ["resume=true", "total_timesteps=240"])
    assert (run_dir / "config.json").read_text() == before
    assert (run_dir / "config_resume.json").exists()

    # A resume NEVER writes the canonical snapshot — even when it is
    # missing (pre-feature run), so config.json can't claim resume cfg
    # was the original training config.
    (run_dir / "config.json").unlink()
    shim.main(args + ["resume=true", "total_timesteps=360"])
    assert not (run_dir / "config.json").exists()


def test_ppo_from_config_null_schedule_knobs():
    """Explicit null overrides of the optional schedule knobs must parse
    as 'off', not crash (log_std_decay_start=null used to hit
    float(None))."""
    cfg = load_config(
        [
            "name=x",
            "ent_coef_final=null",
            "log_std_final=null",
            "log_std_decay_start=null",
        ]
    )
    ppo = train_cli.ppo_from_config(cfg)
    assert ppo.ent_coef_final is None
    assert ppo.log_std_final is None
    assert ppo.log_std_decay_start == 0.0


def test_ppo_from_config_schedule_knobs_forwarded():
    cfg = load_config(
        ["name=x", "log_std_final=-2.5", "log_std_decay_start=0.5"]
    )
    ppo = train_cli.ppo_from_config(cfg)
    assert ppo.log_std_final == -2.5
    assert ppo.log_std_decay_start == 0.5


def test_hidden_sizes_knob():
    """hidden_sizes=[...] (the SB3 policy_kwargs/net_arch analog) reaches
    the constructed model; null keeps the reference 'MlpPolicy' default."""
    cfg = load_config(
        ["name=x", "hidden_sizes=[128,128]", "num_formation=4",
         "num_agents_per_formation=3"]
    )
    trainer = train_cli.build_trainer(cfg)
    assert tuple(trainer.model.hidden) == (128, 128)
    cfg2 = load_config(
        ["name=x", "num_formation=4", "num_agents_per_formation=3"]
    )
    assert tuple(train_cli.build_trainer(cfg2).model.hidden) == (64, 64)
