"""gymnasium.vector.VectorEnv adapter (compat/gym_vector_env.py).

Pins the vector API contract (spaces, shapes, SAME_STEP autoreset
declaration), semantic agreement with the single-env adapter, and the
truncation timing the reference's timeout-only episodes imply.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

gym = pytest.importorskip("gymnasium")

from marl_distributedformation_tpu.compat.gym_env import (  # noqa: E402
    FormationGymEnv,
)
from marl_distributedformation_tpu.compat.gym_vector_env import (  # noqa: E402
    FormationVectorEnv,
)
from marl_distributedformation_tpu.env import EnvParams  # noqa: E402


def test_vector_api_contract():
    env = FormationVectorEnv(EnvParams(num_agents=4, max_steps=8), num_envs=3)
    assert env.metadata["autoreset_mode"] == gym.vector.AutoresetMode.SAME_STEP
    assert env.single_observation_space.shape == (4, env.params.obs_dim)
    assert env.single_action_space.shape == (4, 2)
    assert env.observation_space.shape == (3, 4, env.params.obs_dim)
    obs, info = env.reset(seed=0)
    assert obs.shape == (3, 4, env.params.obs_dim)
    assert env.observation_space.contains(obs)
    act = np.asarray(env.action_space.sample(), np.float32)
    obs2, rewards, terminated, truncated, infos = env.step(act)
    assert obs2.shape == obs.shape
    assert rewards.shape == terminated.shape == truncated.shape == (3,)
    assert terminated.dtype == truncated.dtype == bool
    assert infos["steps"].tolist() == [1, 1, 1]
    assert "avg_dist_to_goal" in infos
    env.close()


def test_matches_single_env_semantics():
    """Formation 0 of the vector env == the single-env adapter under the
    same seed: the vector adapter is pure batching, not a reimplement."""
    params = EnvParams(num_agents=3)
    vec = FormationVectorEnv(params, num_envs=1)
    single = FormationGymEnv(params)
    ov, _ = vec.reset(seed=11)
    os_, _ = single.reset(seed=11)
    np.testing.assert_array_equal(ov[0], os_)
    act = np.full((3, 2), 0.25, np.float32)
    for _ in range(3):
        ov, rv, tv, cv, _ = vec.step(act[None])
        os_, rs, ts, cs, _ = single.step(act)
    np.testing.assert_array_equal(ov[0], os_)
    np.testing.assert_allclose(rv[0], rs, rtol=1e-6)
    assert bool(tv[0]) == ts and bool(cv[0]) == cs


def test_truncates_and_autoresets_same_step():
    env = FormationVectorEnv(
        EnvParams(num_agents=3, max_steps=8), num_envs=2
    )
    env.reset(seed=0)
    act = np.zeros((2, 3, 2), np.float32)
    for i in range(1, 11):
        obs, _, terminated, truncated, infos = env.step(act)
        assert not terminated.any()  # timeout-only episodes (Q3)
        if truncated.all():
            break
    assert i == 10  # max_steps + 2 (Q1 off-by-one, deliberate)
    # SAME_STEP autoreset: the step that truncates already returns the
    # next episode's first obs and resets the step counters.
    assert infos["steps"].tolist() == [10, 10]
    obs2, _, _, truncated2, infos2 = env.step(act)
    assert not truncated2.any()
    assert infos2["steps"].tolist() == [1, 1]
    assert np.isfinite(obs2).all()


def test_standard_vector_wrapper_composes():
    """A stock gymnasium vector wrapper (RecordEpisodeStatistics) drives
    the adapter unchanged — the ecosystem-interop claim, exercised."""
    env = FormationVectorEnv(
        EnvParams(num_agents=3, max_steps=8), num_envs=2
    )
    wrapped = gym.wrappers.vector.RecordEpisodeStatistics(env)
    wrapped.reset(seed=0)
    act = np.zeros((2, 3, 2), np.float32)
    stats = None
    for _ in range(10):
        _, _, _, _, infos = wrapped.step(act)
        if "episode" in infos:
            stats = infos["episode"]
    assert stats is not None, "wrapper never reported episode stats"
    assert np.asarray(stats["l"]).tolist() == [10, 10]  # Q1 episode length
    assert np.isfinite(np.asarray(stats["r"])).all()
