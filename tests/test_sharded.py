"""Sharded serving, the earned ladder, and SLO classes (tier-1,
multi-device CPU): the acceptance pins from the sharded-serving ISSUE,
on the 8-virtual-device mesh tests/conftest.py provisions:

- mesh-sharded rungs produce BITWISE the replicated engine's f32
  actions at every rung (dp sharding replicates params and splits the
  batch — same per-row program, so the gate is equality, not a
  tolerance), deterministic AND stochastic;
- bf16 rungs diverge within the explicit cast-rounding budget
  (tests/bf16_budget.py), never bitwise-silently serving f32;
- the ladder autotuner is deterministic given a fixed trace and its DP
  is exactly minimal against brute force;
- SLO-class admission: an interactive request is NEVER rejected while
  batch traffic is queued (the newest batch request yields, with the
  standard backpressure contract), and queued interactive work
  dispatches ahead of earlier-queued batch work.
"""

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import marl_distributedformation_tpu.jax_compat  # noqa: F401 — bitwise PRNG
import jax
import jax.numpy as jnp

from bf16_budget import bf16_action_atol
from marl_distributedformation_tpu.compat.policy import LoadedPolicy
from marl_distributedformation_tpu.models import MLPActorCritic
from marl_distributedformation_tpu.obs.export import prometheus_exposition
from marl_distributedformation_tpu.parallel.mesh import make_mesh
from marl_distributedformation_tpu.serving import (
    BackpressureError,
    BucketedPolicyEngine,
    MicroBatchScheduler,
    ShardedPolicyEngine,
    ShardedSpec,
    autotune_ladder,
    max_rate_at_slo,
    run_load,
    synthetic_trace,
)
from marl_distributedformation_tpu.serving.autotune import (
    choose_buckets,
    choose_window_ms,
    padded_cost,
)
from marl_distributedformation_tpu.serving.fleet import (
    FleetRouter,
    warmup_fleet,
)
from marl_distributedformation_tpu.serving.loadgen import (
    load_trace,
    save_trace,
)
from marl_distributedformation_tpu.serving.scheduler import (
    SLO_BATCH,
    SLO_INTERACTIVE,
    _ClassedQueue,
    _Request,
)
from marl_distributedformation_tpu.serving.sharded import (
    fit_spec_to_mesh,
    match_partition_rules,
)

OBS_DIM = 6
HIDDEN = (8, 8)
BUCKETS = (8, 64, 512)  # every rung ladder used by the parity gates


def _make_policy(seed=0):
    model = MLPActorCritic(act_dim=2, hidden=HIDDEN)
    variables = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, OBS_DIM))
    )
    return LoadedPolicy(dict(variables), model_kwargs={"hidden": HIDDEN})


def _obs(n, seed=0):
    return (
        np.random.default_rng(seed)
        .standard_normal((n, OBS_DIM))
        .astype(np.float32)
    )


# -- sharded == replicated parity ---------------------------------------


def test_sharded_matches_replicated_bitwise_at_every_rung():
    """dp-sharded rungs are the SAME per-row program as the replicated
    engine — params replicate, only the batch axis splits — so f32
    parity is bitwise equality at every rung, both action modes. The
    engines share seed and dispatch cadence, so the stochastic legs
    fold in identical per-dispatch keys."""
    policy = _make_policy()
    replicated = BucketedPolicyEngine(policy, buckets=BUCKETS, seed=5)
    sharded = ShardedPolicyEngine(
        policy, make_mesh({"dp": 4}), buckets=BUCKETS, seed=5
    )
    for n in BUCKETS:
        obs = _obs(n, seed=n)
        a_rep = replicated.act(obs, deterministic=True)
        a_sh = sharded.act(obs, deterministic=True)
        assert a_rep.dtype == np.float32 == a_sh.dtype
        assert np.array_equal(a_rep, a_sh), f"f32 det parity at rung {n}"
    for n in BUCKETS:
        obs = _obs(n, seed=1000 + n)
        a_rep = replicated.act(obs, deterministic=False)
        a_sh = sharded.act(obs, deterministic=False)
        assert np.array_equal(
            a_rep, a_sh
        ), f"f32 stochastic parity at rung {n}"
    # Both modes rode ONE compiled program per rung (traced bool).
    assert all(c == 1 for c in sharded.compile_counts().values())
    assert all(c == 1 for c in replicated.compile_counts().values())


def test_bf16_rungs_within_cast_rounding_budget():
    """bf16 rungs actually compute in bf16 (divergence is nonzero) and
    the deterministic-action divergence vs the f32 ladder stays inside
    the explicit cast-rounding budget — tests/bf16_budget.py's bound,
    not a flat tolerance."""
    policy = _make_policy()
    replicated = BucketedPolicyEngine(policy, buckets=BUCKETS)
    bf16 = ShardedPolicyEngine(
        policy, make_mesh({"dp": 4}), buckets=BUCKETS, dtype="bfloat16"
    )
    assert bf16.dtype_label == "bf16"
    atol = bf16_action_atol(num_layers=len(HIDDEN) + 1)
    for n in BUCKETS:
        obs = _obs(n, seed=n)
        a32 = replicated.act(obs, deterministic=True)
        a16 = bf16.act(obs, deterministic=True)
        assert a16.dtype == np.float32  # actions come back f32
        diff = np.max(np.abs(a32 - a16))
        assert 0.0 < diff <= atol, (
            f"rung {n}: bf16 divergence {diff:.2e} outside (0, {atol:.2e}]"
        )


def test_mp_axis_shards_kernels_and_stays_within_fp_noise():
    """A dp×mp mesh splits tower kernels over their OUTPUT features.
    The next layer then contracts over an mp-sharded activation, which
    re-orders that reduction — so the mp gate is fp-reduction noise,
    not bitwise (the dp-only fleet default keeps the bitwise gate)."""
    policy = _make_policy()
    mesh = make_mesh({"dp": 2, "mp": 2})
    engine = ShardedPolicyEngine(policy, mesh, buckets=(8,))
    specs = [
        (name, spec)
        for name, spec in _named_specs(engine.param_specs)
        if "mp" in tuple(spec)
    ]
    assert specs, "no param leaf sharded over the mp axis"
    replicated = BucketedPolicyEngine(policy, buckets=(8,))
    obs = _obs(8)
    np.testing.assert_allclose(
        replicated.act(obs, deterministic=True),
        engine.act(obs, deterministic=True),
        rtol=0,
        atol=1e-5,  # reduction-order noise, orders above measured
    )


def _named_specs(spec_tree):
    from marl_distributedformation_tpu.serving.sharded import _tree_paths
    from jax.sharding import PartitionSpec as P

    flat, _ = jax.tree_util.tree_flatten_with_path(
        spec_tree, is_leaf=lambda x: isinstance(x, P)
    )
    return [
        ("/".join(str(getattr(e, "key", e)) for e in path), leaf)
        for path, leaf in flat
    ]


def test_sharded_engine_rejects_bad_mesh_and_buckets():
    policy = _make_policy()
    with pytest.raises(ValueError, match="dp"):
        ShardedPolicyEngine(policy, make_mesh({"sp": 2}), buckets=(8,))
    with pytest.raises(ValueError, match="divide"):
        ShardedPolicyEngine(policy, make_mesh({"dp": 4}), buckets=(6,))


def test_fit_spec_degrades_to_what_the_mesh_supports():
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"dp": 4})
    # Unknown axis -> replicated; known axis keeps only dividing dims.
    assert fit_spec_to_mesh(P(None, "mp"), (8, 8), mesh) == P()
    assert fit_spec_to_mesh(P("dp"), (8, 6), mesh) == P("dp")
    assert fit_spec_to_mesh(P("dp"), (6, 8), mesh) == P()


def test_partition_rules_require_a_match():
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"dp": 2})
    params = {"tower": {"kernel": np.ones((4, 4), np.float32)}}
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules((("nomatch", P()),), params, mesh)
    specs = match_partition_rules(
        ((r"kernel", P("dp")), (r".*", P())), params, mesh
    )
    assert specs["tower"]["kernel"] == P("dp")


# -- fleet routing + rung gauges ----------------------------------------


def test_router_routes_big_rungs_to_the_sharded_replica():
    """Big requests land on the mesh-backed replica, small ones on the
    replicated ladder, and the rung gauges surface both through the
    Prometheus folding (the tracing spine sees the new engine through
    the existing endpoint)."""
    policy = _make_policy()
    router = FleetRouter(
        policy,
        num_replicas=2,
        buckets=(1, 8, 64, 512),
        window_ms=0.0,
        sharded=ShardedSpec(axis_sizes={"dp": 2}, buckets=(64, 512)),
    )
    with router:
        warmup_fleet(router, (OBS_DIM,))
        big = router.submit(_obs(64), timeout_s=30.0).result(60.0)
        small = router.submit(_obs(1), timeout_s=30.0).result(60.0)
        assert big.replica == router.sharded_replica.index
        assert small.replica != router.sharded_replica.index
        snap = router.metrics.snapshot(router.replicas)
    assert snap["rung64_f32_sharded"] == 1.0
    assert snap["rung512_f32_sharded"] == 1.0
    # Compile receipts are kind-attributed: both engine kinds serve the
    # 64 rung here (warmup compiled each once), and folding them into
    # one number would make a receipt breach unattributable.
    assert snap["rung64_f32_sharded_compiles"] == 1.0
    assert snap["rung64_f32_replicated_compiles"] == 1.0
    assert snap["rung512_f32_sharded_compiles"] == 1.0
    text = prometheus_exposition(snap)
    assert (
        'marl_rung_sharded{dtype="f32",rung="64"} 1' in text
        or 'marl_rung_sharded{dtype="f32",rung="64"} 1.0' in text
    )
    assert 'marl_rung_compiles{dtype="f32",kind="sharded",rung="64"}' in text
    assert (
        'marl_rung_compiles{dtype="f32",kind="replicated",rung="64"}'
        in text
    )


# -- the earned ladder ---------------------------------------------------


def test_autotuner_is_deterministic_given_a_fixed_trace():
    """Same trace in, same plan out — twice from one trace object and
    once from an identically-seeded rebuild. An autotuner that flaps on
    identical input would churn compiled rungs."""
    t1 = synthetic_trace(20.0, 40.0, seed=3, batch_fraction=0.2)
    t2 = synthetic_trace(20.0, 40.0, seed=3, batch_fraction=0.2)
    kw = dict(p95_target_ms=50.0, mesh_divisor=4, sharded_min_rows=64)
    p1 = autotune_ladder(t1, **kw)
    p2 = autotune_ladder(t1, **kw)
    p3 = autotune_ladder(t2, **kw)
    assert p1 == p2 == p3
    assert all(b % 4 == 0 for b in p1.sharded_buckets)
    assert set(p1.sharded_buckets) | set(p1.replicated_buckets) == set(
        p1.buckets
    )
    # The earned ladder beats the hand-picked one on its own traffic.
    assert p1.expected_occupancy_pct >= p1.baseline_occupancy_pct


def test_choose_buckets_dp_is_exactly_minimal():
    """The rung DP against brute force: over every candidate subset (of
    the observed sizes, top size always covered) within the rung budget,
    no ladder pads fewer rows than the DP's."""
    import itertools

    sizes = np.array([1, 1, 1, 2, 7, 7, 9, 30, 30, 64], np.int64)
    got = choose_buckets(sizes, max_rungs=3)
    cands = sorted(set(int(s) for s in sizes))
    best = min(
        padded_cost(sizes, combo + (cands[-1],))
        for r in range(0, 3)
        for combo in itertools.combinations(cands[:-1], r)
    )
    assert padded_cost(sizes, got) == best
    assert len(got) <= 3 and max(got) == 64


def test_choose_window_caps_at_slo_fraction_and_shrinks_with_rate():
    slow = choose_window_ms(
        10.0, 1.0, fill_rows=32, p95_target_ms=50.0
    )
    fast = choose_window_ms(
        10_000.0, 1.0, fill_rows=32, p95_target_ms=50.0
    )
    assert slow == pytest.approx(0.2 * 50.0)  # capped, not 3200 ms
    assert 0.0 < fast < slow


def test_trace_roundtrip_and_rate_scaling(tmp_path):
    trace = synthetic_trace(5.0, 30.0, seed=1, batch_fraction=0.3)
    path = tmp_path / "trace.jsonl"
    save_trace(trace, path)
    back = load_trace(path)
    assert np.allclose(back.inter_arrival_s, trace.inter_arrival_s)
    assert np.array_equal(back.sizes, trace.sizes)
    assert back.slo_classes == trace.slo_classes
    doubled = trace.scaled_to_rate(trace.offered_rps * 2)
    assert doubled.offered_rps == pytest.approx(
        trace.offered_rps * 2
    )
    assert np.array_equal(doubled.sizes, trace.sizes)


def test_open_loop_replay_measures_a_live_scheduler():
    """run_load against a real engine: every request completes, the
    report carries per-size percentiles, and the SLO bisection finds a
    nonzero sustainable rate under a generous target."""
    policy = _make_policy()
    engine = BucketedPolicyEngine(policy, buckets=(1, 8))
    with MicroBatchScheduler(engine, window_ms=0.0) as sched:
        engine.act(_obs(1))  # warm both rungs outside the replay
        engine.act(_obs(8))
        trace = synthetic_trace(
            0.4, 150.0, seed=2, size_mix=((1, 0.7), (8, 0.3))
        )
        rep = run_load(sched, trace, (OBS_DIM,), seed=2)
        assert rep.submitted == len(trace)
        assert rep.ok == rep.submitted
        assert rep.p95_ms > 0.0
        assert set(rep.per_size_p95_ms) <= {1, 8}
        assert rep.meets(p95_target_ms=10_000.0, max_loss=0.0)
        best, reports = max_rate_at_slo(
            sched,
            (OBS_DIM,),
            p95_target_ms=500.0,
            lo_rps=20.0,
            hi_rps=80.0,
            probe_duration_s=0.25,
            iterations=1,
            seed=2,
            size_mix=((1, 0.7), (8, 0.3)),
        )
        assert best >= 20.0
        assert len(reports) >= 2


# -- SLO classes ---------------------------------------------------------


def _req(slo, tag):
    obs = np.full((1, OBS_DIM), float(tag), np.float32)
    return _Request(
        obs=obs,
        deterministic=True,
        future=Future(),
        enqueued=time.perf_counter(),
        timeout_s=None,
        slo_class=slo,
    )


def test_classed_queue_orders_interactive_first_fifo_within_class():
    q = _ClassedQueue(maxsize=8)
    b1, b2 = _req(SLO_BATCH, 1), _req(SLO_BATCH, 2)
    i1, i2 = _req(SLO_INTERACTIVE, 3), _req(SLO_INTERACTIVE, 4)
    for r in (b1, b2, i1, i2):
        assert q.put_nowait(r) is None
    assert [q.get_nowait() for _ in range(4)] == [i1, i2, b1, b2]
    with pytest.raises(queue.Empty):
        q.get_nowait()


def test_classed_queue_preempts_newest_batch_never_interactive():
    q = _ClassedQueue(maxsize=3)
    b1, b2, i1 = _req(SLO_BATCH, 1), _req(SLO_BATCH, 2), _req(
        SLO_INTERACTIVE, 3
    )
    for r in (b1, b2, i1):
        assert q.put_nowait(r) is None
    # Full + batch queued: interactive admission evicts the NEWEST
    # batch request (b2 — it has waited least).
    i2 = _req(SLO_INTERACTIVE, 4)
    assert q.put_nowait(i2) is b2
    # Full + batch arrival: plain reject.
    with pytest.raises(queue.Full):
        q.put_nowait(_req(SLO_BATCH, 5))
    # Full + all-interactive: only now may interactive be rejected.
    assert q.put_nowait(_req(SLO_INTERACTIVE, 6)) is b1
    with pytest.raises(queue.Full):
        q.put_nowait(_req(SLO_INTERACTIVE, 7))
    assert q.qsize() == 3


class _GatedEngine:
    """Engine stub whose first dispatch blocks until released, tagging
    dispatch order by the obs fill value."""

    max_bucket = 8

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()
        self.order = []

    def plan(self, n):
        return [self.max_bucket]

    def act(self, obs, deterministic=True, nn_params=None):
        self.entered.set()
        assert self.release.wait(30.0)
        self.order.append(int(obs[0, 0]))
        return np.zeros((obs.shape[0], 2), np.float32)


def test_scheduler_preempts_batch_for_interactive_under_backpressure():
    """End-to-end SLO-class contract through the scheduler: with the
    worker wedged and the queue full of batch work, interactive
    arrivals are admitted (never rejected while batch is queued), the
    evicted batch futures fail with the standard retryable
    backpressure, and the queue drains interactive-first."""
    engine = _GatedEngine()
    sched = MicroBatchScheduler(engine, max_queue=3, window_ms=0.0)
    with sched:
        blocker = sched.submit(
            np.full((1, OBS_DIM), 99.0, np.float32), timeout_s=30.0
        )
        assert engine.entered.wait(10.0)  # worker is mid-dispatch
        batch_futs = [
            sched.submit(
                np.full((1, OBS_DIM), 200.0 + i, np.float32),
                timeout_s=30.0,
                slo_class="batch",
            )
            for i in range(3)
        ]
        # Queue full of batch work: interactive is still admitted —
        # newest batch requests yield, newest-first.
        inter_futs = [
            sched.submit(
                np.full((1, OBS_DIM), 100.0 + i, np.float32),
                timeout_s=30.0,
            )
            for i in range(2)
        ]
        preempted = [f for f in batch_futs if f.done()]
        assert len(preempted) == 2
        for f in (batch_futs[2], batch_futs[1]):
            assert isinstance(f.exception(0), BackpressureError)
        assert f.exception(0).retry_after_s >= 0.0
        assert sched.metrics.preempted_total == 2
        engine.release.set()
        blocker.result(30.0)
        for f in inter_futs:
            f.result(30.0)
        batch_futs[0].result(30.0)
    # The surviving batch request (200) dispatched AFTER both
    # interactive requests despite enqueueing first.
    assert engine.order[0] == 99
    assert engine.order[1:3] == [100, 101]
    assert engine.order[3] == 200


def test_building_a_sharded_engine_never_invalidates_a_warmed_engine():
    """Construction-order hazard pin: a replicated engine warmed BEFORE
    the process's first mesh-sharded engine exists must keep serving
    without retraces after one is built. jax config values key the jit
    cache, and the sharded stack's lazy ``parallel.mesh`` import runs
    jax_compat's global PRNG normalization (jax_threefry_partitionable)
    — serving/engine.py therefore imports jax_compat itself, so the
    config is final before ANY engine's first compile. Run in a fresh
    interpreter: this suite (like most entry points) already imports
    jax_compat at startup, which would mask the ordering."""
    import subprocess
    import sys

    code = """
import numpy as np
from marl_distributedformation_tpu.compat.policy import LoadedPolicy
from marl_distributedformation_tpu.models import MLPActorCritic
from marl_distributedformation_tpu.serving import (
    BucketedPolicyEngine, ShardedPolicyEngine,
)
import jax, jax.numpy as jnp

model = MLPActorCritic(act_dim=2)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
policy = LoadedPolicy(dict(variables))
replicated = BucketedPolicyEngine(policy, buckets=(8,))
obs = np.ones((4, 8), np.float32)
replicated.act(obs)  # warm: the rung's one budgeted trace

from marl_distributedformation_tpu.parallel.mesh import make_mesh
sharded = ShardedPolicyEngine(policy, make_mesh({"dp": 2}), buckets=(8,))
sharded.act(obs)

replicated.act(obs)  # would RetraceError if the build flipped config
assert replicated.compile_counts() == {8: 1}, replicated.compile_counts()
print("OK")
"""
    env = {
        **__import__("os").environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
        cwd=str(__import__("pathlib").Path(__file__).parent.parent),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout


def test_autotuner_zeroes_the_dedicated_lanes_window():
    """A routing floor that fills the slice's smallest rung on arrival
    earns window 0 for that lane (nothing to coalesce — waiting is pure
    latency); a floor below the rung (partial-rung requests pad up)
    keeps the global window."""
    trace = synthetic_trace(
        2.0, 200.0, seed=3, size_mix=((1, 0.5), (8, 0.3), (512, 0.2))
    )
    filled = autotune_ladder(
        trace, p95_target_ms=50.0, mesh_divisor=2, sharded_min_rows=512
    )
    assert filled.sharded_buckets and min(filled.sharded_buckets) == 512
    assert filled.sharded_window_ms == 0.0
    partial = autotune_ladder(
        trace, p95_target_ms=50.0, mesh_divisor=2, sharded_min_rows=100
    )
    assert partial.sharded_buckets and min(partial.sharded_buckets) > 100
    assert partial.sharded_window_ms == partial.window_ms > 0.0


def test_router_gives_the_sharded_lane_its_own_window():
    """ShardedSpec.window_ms overrides the fleet window for the slice's
    scheduler only; None inherits."""
    policy = _make_policy()
    spec = ShardedSpec(
        axis_sizes={"dp": 2}, buckets=(64,), min_rows=64, window_ms=0.0
    )
    with FleetRouter(
        policy, num_replicas=1, buckets=(1, 64), window_ms=2.0,
        sharded=spec,
    ) as router:
        by_kind = {r.kind: r for r in router.replicas}
        assert by_kind["sharded"].scheduler.window_s == 0.0
        assert by_kind["replicated"].scheduler.window_s == 0.002
