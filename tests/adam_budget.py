"""Adam-amplification tolerance budget for sharding/multiprocess parity
gates (ROADMAP "Open items" analysis, made explicit).

The facts the budget is built from:

1. **Base noise.** A batched-one-device XLA program and its
   per-device-sharded lowering reduce sums in different orders; measured
   on jax 0.4.37/CPU, a single minibatch gradient matches between the
   two to ~3e-8 relative — pure fp reduction-order noise, not a bug.
2. **Adam amplification.** Adam's update is
   ``lr * m_hat / (sqrt(v_hat) + eps)`` — *normalized*: the update
   magnitude is ~``lr`` per parameter regardless of gradient scale. A
   perturbation of ANY size (even 3e-8) can flip the sign of a
   near-zero ``m_hat`` component, so two runs from the same init can
   legitimately drift apart by up to ``2 * lr`` per parameter per
   update, compounding through the on-policy trajectory.
3. **Measured headroom.** In this container the observed divergence
   after U updates is ~``0.3 * lr * U`` (test_sweep: 2.9e-3 at
   ``lr*U = 8e-3``; test_hetero_sweep: 9.7e-3 at ``lr*U = 3.2e-2``).

So the budget for parameters is ``atol = lr * U`` (3x above observed
noise, and the theoretical half-bound), with ``rtol = 0`` — Adam steps
are absolute-scaled, so an absolute tolerance is the principled unit.
A flat ``rtol=1e-4`` (the old gate) was wrong in BOTH directions: it
failed on legitimate fp noise for near-zero parameters and would have
passed garbage for large ones.

Scalar training metrics (reward/loss) feel a parameter perturbation
through the whole rollout; their *relative* divergence tracks
``lr * U`` with a trajectory sensitivity factor — calibrated at 30x
(observed sweep reward rel-divergence is ~1e-3 at ``lr*U = 8e-3``,
i.e. factor ~0.1; 30 covers episode-boundary discontinuities, where a
near-done formation can flip which side of the reset a step lands on).
"""

# Measured single-minibatch sharded-vs-unsharded gradient mismatch:
# fp reduction-order noise between XLA lowerings (jax 0.4.37, CPU).
FP_REDUCTION_NOISE = 3e-8


def updates_per_run(ppo, rows_per_iter: int, iterations: int) -> int:
    """Optimizer steps a run of ``iterations`` trainer iterations takes:
    ``n_epochs * (usable minibatches)`` per iteration, mirroring
    algo.ppo's clamp-and-drop-remainder minibatching."""
    batch = min(ppo.batch_size, rows_per_iter)
    return iterations * ppo.n_epochs * (rows_per_iter // batch)


def adam_parity_atol(lr: float, num_updates: int) -> float:
    """Parameter-space budget: up to ~lr of normalized-update drift per
    Adam step once fp noise breaks the tie, summed over updates. Use
    with ``rtol=0`` — see the module docstring for the derivation."""
    return FP_REDUCTION_NOISE + float(lr) * num_updates


def trajectory_rtol(
    lr: float, num_updates: int, sensitivity: float = 30.0
) -> float:
    """Relative budget for scalar rollout metrics (reward, loss) of two
    runs whose parameters diverged within ``adam_parity_atol``."""
    return sensitivity * float(lr) * num_updates
