"""gymnasium.Env adapter (compat/gym_env.py).

gymnasium's own ``check_env`` validates the full API contract; the rest
pins the semantics the adapter promises: action scaling parity with the
vec adapter, truncation at the reference's episode length, and seeded
determinism.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

gym = pytest.importorskip("gymnasium")

from marl_distributedformation_tpu.compat.gym_env import (  # noqa: E402
    FormationGymEnv,
)
from marl_distributedformation_tpu.env import EnvParams  # noqa: E402


def test_gymnasium_check_env():
    from gymnasium.utils.env_checker import check_env

    env = FormationGymEnv(EnvParams(num_agents=4, max_steps=16))
    # skip_render_check: human mode needs a display; rgb_array is covered
    # by test_render_rgb_array below.
    check_env(env, skip_render_check=True)


def test_truncates_at_reference_episode_length():
    """strict_parity episodes run max_steps + 2 steps (SURVEY.md Q1) and
    end by TRUNCATION, not termination (timeout-only, Q3)."""
    env = FormationGymEnv(EnvParams(num_agents=3, max_steps=16))
    env.reset(seed=0)
    act = np.zeros((3, 2), np.float32)
    for i in range(1, 19):
        _, _, terminated, truncated, info = env.step(act)
        assert not terminated
        if truncated:
            break
    assert truncated and i == 18  # 16 + 2 (Q1 off-by-one, deliberate)


def test_seeded_determinism_and_reward():
    env = FormationGymEnv(EnvParams(num_agents=3))
    obs_a, _ = env.reset(seed=7)
    env_b = FormationGymEnv(EnvParams(num_agents=3))
    obs_b, _ = env_b.reset(seed=7)
    np.testing.assert_array_equal(obs_a, obs_b)

    act = np.full((3, 2), 0.5, np.float32)
    oa, ra, *_ = env.step(act)
    ob, rb, *_ = env_b.step(act)
    np.testing.assert_array_equal(oa, ob)
    assert ra == rb and np.isfinite(ra)


def test_action_scaling_matches_vec_adapter():
    """The gym env scales [-1,1] actions by max_speed exactly like
    FormationVecEnv (reference vectorized_env.py:69-70)."""
    from marl_distributedformation_tpu.compat.vec_env import FormationVecEnv

    params = EnvParams(num_agents=3)
    genv = FormationGymEnv(params)
    venv = FormationVecEnv(params, num_formations=1, seed=3)
    obs_g, _ = genv.reset(seed=3)
    obs_v = venv.reset()
    np.testing.assert_array_equal(obs_g.reshape(-1), obs_v.reshape(-1))

    act = np.random.default_rng(0).uniform(-1, 1, (3, 2)).astype(np.float32)
    obs_g2, rew_g, *_ = genv.step(act)
    obs_v2, rew_v, *_ = venv.step(act.reshape(3, 2))
    np.testing.assert_array_equal(obs_g2.reshape(-1), obs_v2.reshape(-1))
    assert rew_g == pytest.approx(float(rew_v.mean()), rel=1e-6)


def test_knn_obs_within_declared_bounds():
    """knn observations carry raw neighbor indices; the declared Box must
    actually contain them (check_env enforces containment)."""
    from gymnasium.utils.env_checker import check_env

    env = FormationGymEnv(
        EnvParams(num_agents=6, obs_mode="knn", knn_k=2, max_steps=8)
    )
    assert env.observation_space.high.max() == 5.0
    check_env(env, skip_render_check=True)


def test_goal_termination_vs_timeout_distinction():
    """Off-parity with goal_termination: a done at the step limit is
    TRUNCATION (value bootstrap), not termination — even though the env
    ORs both conditions into one done flag."""
    env = FormationGymEnv(
        EnvParams(
            num_agents=3,
            max_steps=8,
            strict_parity=False,
            goal_termination=True,
        )
    )
    env.reset(seed=1)
    act = np.zeros((3, 2), np.float32)
    for _ in range(8):
        _, _, terminated, truncated, _ = env.step(act)
        if terminated or truncated:
            break
    # Zero actions never reach the goal: the step-limit done must be
    # reported as truncation despite goal_termination being enabled.
    assert truncated and not terminated


def test_render_before_reset_is_a_clear_error():
    env = FormationGymEnv(EnvParams(num_agents=3), render_mode="rgb_array")
    with pytest.raises(AssertionError, match="reset"):
        env.render()


def test_render_rgb_array():
    env = FormationGymEnv(
        EnvParams(num_agents=3), render_mode="rgb_array"
    )
    env.reset(seed=0)
    env.step(np.zeros((3, 2), np.float32))
    frame = env.render()
    assert frame.ndim == 3 and frame.shape[-1] == 3 and frame.size > 0
    env.close()
