"""Unit tests for the whole-repo call-graph + lock-context engine
(`analysis/callgraph.py`): annotation parsing, thread-target discovery,
transitive lock context, cycle detection, and the mtime-keyed cache."""

import ast
import os
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from marl_distributedformation_tpu.analysis.callgraph import (  # noqa: E402
    LOCK_ORDERING_CYCLE,
    UNGUARDED_SHARED_MUTATION,
    CallGraphEngine,
    ModuleInfo,
    PackageGraph,
    parse_annotations,
)
from marl_distributedformation_tpu.analysis.linter import (  # noqa: E402
    ModuleContext,
)


def graph(src: str) -> PackageGraph:
    """One in-memory module, analyzed alone (the fixture path)."""
    source = textwrap.dedent(src)
    mod = ModuleInfo("mem.py", ast.parse(source), source)
    return PackageGraph({"mem.py": mod}, CallGraphEngine())


# ---------------------------------------------------------------------------
# Annotation grammar
# ---------------------------------------------------------------------------


def test_parse_annotations_guarded_by():
    out = parse_annotations("self.step = 0  # graftlock: guarded-by=_lock")
    assert out == {"guarded-by": ["_lock"]}


def test_parse_annotations_trailing_prose_is_ignored():
    # Parsing stops at the first non-key token: annotation lines can
    # carry human prose after the payload without corrupting it.
    out = parse_annotations(
        "last_beat: float  # graftlock: guarded-by=_hosts_lock — monotonic"
    )
    assert out == {"guarded-by": ["_hosts_lock"]}


def test_parse_annotations_gate_and_multiple_keys():
    out = parse_annotations(
        "self._g = threading.Lock()  # graftlock: gate lock=_g"
    )
    assert out == {"gate": [], "lock": ["_g"]}


def test_parse_annotations_absent():
    assert parse_annotations("self.step = 0  # plain comment") == {}


# ---------------------------------------------------------------------------
# Thread-target discovery
# ---------------------------------------------------------------------------


def test_thread_target_discovery():
    pg = graph(
        """
        import threading

        class Server:
            def __init__(self, pool):
                self._pool = pool

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()
                threading.Timer(1.0, self._tick).start()
                self._pool.submit(self._job)
                serve({"register": self._rpc_register})

            def _worker(self):
                pass

            def _tick(self):
                pass

            def _job(self):
                pass

            def _rpc_register(self, msg):
                pass
        """
    )
    entries = {f.qualname for f in pg._thread_entries()}
    assert entries == {
        "Server._worker",
        "Server._tick",
        "Server._job",
        "Server._rpc_register",
    }


# ---------------------------------------------------------------------------
# Transitive lock context
# ---------------------------------------------------------------------------

_STORE = """
    import threading

    class Store:
        def __init__(self):
            self.read_lock = threading.Lock()
            self.write_lock = threading.Lock()

        def flush(self):
            with self.read_lock:
                self._sync()

        def _sync(self):
            with self.write_lock:
                pass
"""


def test_lock_edge_through_call_chain():
    # flush never mentions write_lock — the edge exists only because
    # the held context flows through the flush -> _sync call.
    pg = graph(_STORE)
    edges = {
        (a.rsplit(".", 1)[-1], b.rsplit(".", 1)[-1])
        for a, b in pg.lock_edges
    }
    assert ("read_lock", "write_lock") in edges
    site = next(
        s
        for (a, b), s in pg.lock_edges.items()
        if b.endswith("write_lock")
    )
    assert site.qualname == "Store._sync"
    assert any(k.endswith("read_lock") for k in site.chain)


def test_timed_acquire_creates_no_edge():
    pg = graph(
        """
        import threading

        class Store:
            def __init__(self):
                self.read_lock = threading.Lock()
                self.write_lock = threading.Lock()

            def compact(self):
                with self.read_lock:
                    if self.write_lock.acquire(timeout=1.0):
                        self.write_lock.release()
        """
    )
    assert pg.lock_edges == {}


def test_holds_annotation_seeds_held_context():
    pg = graph(
        """
        import threading

        class Store:
            def __init__(self):
                self.read_lock = threading.Lock()
                self.write_lock = threading.Lock()

            # graftlock: holds=read_lock
            def _commit_locked(self):
                with self.write_lock:
                    pass
        """
    )
    edges = {
        (a.rsplit(".", 1)[-1], b.rsplit(".", 1)[-1])
        for a, b in pg.lock_edges
    }
    assert ("read_lock", "write_lock") in edges


# ---------------------------------------------------------------------------
# Cycle detection
# ---------------------------------------------------------------------------


def test_three_lock_cycle_reports_full_acquisition_chain():
    pg = graph(
        """
        import threading

        class Pool:
            def __init__(self):
                self.a_lock = threading.Lock()
                self.b_lock = threading.Lock()
                self.c_lock = threading.Lock()

            def ab(self):
                with self.a_lock:
                    with self.b_lock:
                        pass

            def bc(self):
                with self.b_lock:
                    with self.c_lock:
                        pass

            def ca(self):
                with self.c_lock:
                    with self.a_lock:
                        pass
        """
    )
    found = pg.findings_for("mem.py", LOCK_ORDERING_CYCLE)
    assert len(found) == 1
    (_, _, msg) = found[0]
    # The full chain: every edge of the ring, each with its owning
    # function and file:line, joined into one message.
    assert msg.count("holding") == 3
    for qualname in ("Pool.ab", "Pool.bc", "Pool.ca"):
        assert qualname in msg
    for lock in ("a_lock", "b_lock", "c_lock"):
        assert lock in msg
    assert "mem.py:" in msg


def test_consistent_order_has_no_cycle():
    pg = graph(
        """
        import threading

        class Pool:
            def __init__(self):
                self.a_lock = threading.Lock()
                self.b_lock = threading.Lock()

            def ab(self):
                with self.a_lock:
                    with self.b_lock:
                        pass

            def also_ab(self):
                with self.a_lock:
                    with self.b_lock:
                        pass
        """
    )
    assert pg.findings_for("mem.py", LOCK_ORDERING_CYCLE) == []


# ---------------------------------------------------------------------------
# Cache invalidation: edit a module, the graph re-resolves
# ---------------------------------------------------------------------------


def _all_messages(pg: PackageGraph):
    return [
        msg
        for per_rule in pg.findings.values()
        for msgs in per_rule.values()
        for (_, _, msg) in msgs
    ]


def test_cache_invalidation_on_module_edit(tmp_path):
    helper = tmp_path / "helper.py"
    main = tmp_path / "main.py"
    helper.write_text(
        textwrap.dedent(
            """
            def bump(c):
                pass
            """
        )
    )
    main.write_text(
        textwrap.dedent(
            """
            import threading
            from helper import bump

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.total = 0  # graftlock: guarded-by=_lock

                def start(self):
                    threading.Thread(target=self._worker).start()

                def _worker(self):
                    bump(self)
            """
        )
    )
    eng = CallGraphEngine()

    def analyze() -> PackageGraph:
        mod = eng.module(main)
        ctx = ModuleContext(mod.tree, "\n".join(mod.lines), mod.path)
        return eng.package_for(ctx)

    first = analyze()
    assert _all_messages(first) == []

    # Same snapshot -> the cached PackageGraph is returned as-is.
    assert analyze() is first

    # Edit ONLY the helper: the cross-module write now violates main's
    # guarded-by declaration. The package snapshot (mtime_ns, size)
    # changes, so the graph must re-resolve without a process restart.
    helper.write_text(
        textwrap.dedent(
            """
            def bump(c):
                c.total = c.total + 1
            """
        )
    )
    st = helper.stat()
    os.utime(helper, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))

    second = analyze()
    assert second is not first
    hits = second.findings_for(str(helper), UNGUARDED_SHARED_MUTATION)
    assert len(hits) == 1
    assert "guarded-by='_lock'" in hits[0][2]
