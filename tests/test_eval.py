"""Evaluation harness (eval.py / evaluate.py): episode accounting and the
policy-vs-baseline comparison contract."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.eval import (
    baseline_act_fn,
    episode_length,
    evaluate,
    policy_act_fn,
    zero_act_fn,
)


def short_params(**kw):
    return EnvParams(num_agents=4, max_steps=30, **kw)


def test_episode_length_parity_modes():
    assert episode_length(short_params()) == 32  # Q1 off-by-one
    assert episode_length(short_params(strict_parity=False)) == 30


@pytest.mark.parametrize("strict", [True, False])
def test_exactly_one_episode_and_pre_reset_final_metrics(strict):
    """Every formation finishes exactly one episode, and the reported
    final metrics come from the last pre-reset step (the done row's
    metrics describe a fresh formation — reference step order,
    simulate.py:113-117)."""
    params = short_params(strict_parity=strict)
    out = evaluate(zero_act_fn(), params, num_formations=8, seed=5)
    assert out["episodes"] == 8.0
    # Zero actions: agents spawn in the bottom strip, goal is far — the
    # pre-reset distance must reflect that scattered start, not a
    # post-reset re-randomization that could accidentally be closer.
    assert out["final_avg_dist_to_goal"] > 100.0


def test_baseline_beats_zero_actions():
    # N=10, the reference's own demo size (simulate.py:324). At very small
    # N the scripted controller's radius-40 spacing (Q11) lands deep in the
    # reward's quadratic too-close penalty and actually scores WORSE than
    # zero actions — e.g. N=4: spacing 31.4 vs desired 84.9 is ~-57/step.
    params = EnvParams(num_agents=10, max_steps=300)
    base = evaluate(baseline_act_fn(params), params, num_formations=8)
    zero = evaluate(zero_act_fn(), params, num_formations=8)
    assert (
        base["episode_return_per_agent"] > zero["episode_return_per_agent"]
    )
    assert base["final_avg_dist_to_goal"] < zero["final_avg_dist_to_goal"]


def test_policy_act_fn_scales_and_clips():
    """The policy ActFn applies the L1 adapter semantics: mode action
    clipped to [-1, 1] then scaled by max_speed (vectorized_env.py:69-70)."""

    class HugeMean:
        per_formation = False

        def apply(self, params, obs):
            mean = jnp.full((obs.shape[0], 2), 7.0)
            return mean, jnp.zeros(2), jnp.zeros(obs.shape[0])

    params = short_params()
    act = policy_act_fn(HugeMean(), {}, params)
    obs = jnp.zeros((3, params.num_agents, params.obs_dim))
    vel = act(None, None, None, obs, jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(vel), params.max_speed)


def test_policy_act_fn_stochastic_samples():
    """deterministic=False samples mean + exp(log_std)·eps (SB3's
    evaluate_policy knob); the sample is key-driven and clipped before
    max_speed scaling."""

    class ZeroMeanWideStd:
        per_formation = False

        def apply(self, params, obs):
            mean = jnp.zeros((obs.shape[0], 2))
            return mean, jnp.full(2, -1.0), jnp.zeros(obs.shape[0])

    params = short_params()
    act = policy_act_fn(ZeroMeanWideStd(), {}, params, deterministic=False)
    obs = jnp.zeros((3, params.num_agents, params.obs_dim))
    v1 = act(None, None, None, obs, jax.random.PRNGKey(0))
    v2 = act(None, None, None, obs, jax.random.PRNGKey(0))
    v3 = act(None, None, None, obs, jax.random.PRNGKey(1))
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2))  # key-driven
    assert np.abs(np.asarray(v1) - np.asarray(v3)).max() > 0  # varies by key
    assert np.abs(np.asarray(v1)).max() <= params.max_speed  # clipped
    # std = e^-1 ~ 0.37: samples are non-degenerate around the zero mean
    assert np.abs(np.asarray(v1)).max() > 0


def test_evaluate_cli_roundtrip(tmp_path, monkeypatch, capsys):
    """evaluate.py discovers the latest checkpoint of a named run and
    emits the machine-readable JSON line with the comparison fields."""
    import sys

    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import evaluate as evaluate_cli
    import train as train_cli

    monkeypatch.setattr(
        "marl_distributedformation_tpu.utils.repo_root", lambda: tmp_path
    )
    train_cli.main(
        [
            "name=evalrun",
            "num_formation=4",
            "total_timesteps=800",
            "max_steps=20",
            "strict_parity=false",
        ]
    )
    result = evaluate_cli.main(
        [
            "name=evalrun",
            "eval_formations=4",
            "max_steps=20",
            "strict_parity=false",
        ]
    )
    out = capsys.readouterr().out
    last_json = json.loads(out.strip().splitlines()[-1])
    for key in (
        "policy_episode_return_per_agent",
        "baseline_episode_return_per_agent",
        "zero_episode_return_per_agent",
        "beats_baseline",
    ):
        assert key in last_json, key
    assert result["eval_formations"] == 4


@pytest.mark.slow
def test_evaluate_cli_sweep_mode(tmp_path, capsys):
    """name= pointing at a sweep run evaluates every member and ranks by
    held-out return."""
    import sys

    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import evaluate as evaluate_cli
    import train as train_cli

    train_cli.main(
        [
            "name=evalsweep",
            "num_seeds=2",
            "num_formation=4",
            "total_timesteps=720",
            "n_steps=4",
            "batch_size=24",
            "n_epochs=2",
            "max_steps=20",
            "num_agents_per_formation=3",
            "strict_parity=false",
        ]
    )
    result = evaluate_cli.main(
        [
            "name=evalsweep",
            "eval_formations=4",
            "max_steps=20",
            "num_agents_per_formation=3",
            "strict_parity=false",
        ]
    )
    assert result["sweep_members"] == 2
    assert set(result["member_returns"]) == {"seed0", "seed1"}
    assert result["best_member"] in ("seed0", "seed1")
    assert "baseline_return" in result
