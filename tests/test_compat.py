"""Tests for the host-side compat layer and the reference-workflow frontends."""

import sys
from pathlib import Path

import jax
import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.compat import FormationVecEnv, LoadedPolicy
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.train import TrainConfig, Trainer
from marl_distributedformation_tpu.utils import latest_checkpoint


def test_vec_env_contract():
    """The reference FormationEnv surface (vectorized_env.py:52-82):
    flattened M*N rows, [-1,1] actions scaled x10, done broadcast."""
    env = FormationVecEnv(EnvParams(num_agents=3), num_formations=4, seed=0)
    assert env.num_envs == 12
    obs = env.reset()
    assert obs.shape == (12, 8)
    assert env.observation_space.shape == (8,)
    assert env.action_space.shape == (2,)
    actions = np.zeros((12, 2), np.float32)
    obs2, rewards, dones, infos = env.step(actions)
    assert obs2.shape == (12, 8)
    assert rewards.shape == (12,)
    assert dones.shape == (12,) and dones.dtype == bool
    assert infos == [{}] * 12  # Q4 parity: infos always empty
    # done broadcast per formation: all agents of a formation share it.
    assert (dones.reshape(4, 3) == dones.reshape(4, 3)[:, :1]).all()


def test_vec_env_seed_determinism():
    e1 = FormationVecEnv(EnvParams(num_agents=3), num_formations=2, seed=5)
    e2 = FormationVecEnv(EnvParams(num_agents=3), num_formations=2, seed=5)
    e3 = FormationVecEnv(EnvParams(num_agents=3), num_formations=2, seed=6)
    r1 = e1.reset()
    np.testing.assert_array_equal(r1, e2.reset())
    # Compare FIRST resets so a seed-ignoring regression can't hide behind
    # key-split drift.
    assert not np.allclose(r1, e3.reset())


def test_vec_env_velocity_contract():
    """step_velocities drives the L0 raw-velocity API (SURVEY.md Q8)."""
    env = FormationVecEnv(EnvParams(num_agents=2), num_formations=1, seed=1)
    env.reset()
    before = env.agents_np().copy()
    vel = np.array([[[3.0, 4.0], [-2.0, 1.0]]], np.float32)
    env.step_velocities(vel)
    moved = env.agents_np() - before
    np.testing.assert_allclose(moved, vel[0], atol=1e-4)


def _train_tiny(tmp_path, name="viz"):
    trainer = Trainer(
        EnvParams(num_agents=3),
        ppo=PPOConfig(n_steps=4, batch_size=24, n_epochs=1),
        config=TrainConfig(
            num_formations=2,
            total_timesteps=2 * 3 * 4 * 2,
            name=name,
            log_dir=str(tmp_path / "logs" / name),
        ),
    )
    trainer.train()
    return trainer


def test_loaded_policy_roundtrip(tmp_path):
    trainer = _train_tiny(tmp_path)
    path = latest_checkpoint(tmp_path / "logs" / "viz")
    policy = LoadedPolicy.from_checkpoint(path)
    obs = np.random.default_rng(0).normal(size=(6, 8)).astype(np.float32)
    actions, _ = policy.predict(obs, deterministic=True)
    assert actions.shape == (6, 2)
    assert (np.abs(actions) <= 1.0).all()
    # Deterministic predictions equal the trained policy mean.
    mean, _, _ = trainer.train_state.apply_fn(
        trainer.train_state.params, jax.numpy.asarray(obs)
    )
    np.testing.assert_allclose(
        actions, np.clip(np.asarray(mean), -1, 1), atol=1e-6
    )
    # Stochastic predictions differ across calls but stay in bounds.
    s1, _ = policy.predict(obs, deterministic=False)
    s2, _ = policy.predict(obs, deterministic=False)
    assert not np.allclose(s1, s2)
    assert (np.abs(s1) <= 1.0).all()


def test_loaded_policy_rejects_garbage(tmp_path):
    bad = tmp_path / "rl_model_1_steps.msgpack"
    from flax import serialization

    bad.write_bytes(serialization.to_bytes({"not_params": 1}))
    with pytest.raises(ValueError, match="does not look like"):
        LoadedPolicy.from_checkpoint(bad)


def test_simulate_headless_runs(capsys):
    import simulate

    simulate.main(["headless=true", "steps=30", "num_agents=4", "seed=3"])
    out = capsys.readouterr().out
    assert "avg_dist_to_goal" in out


def test_visualize_policy_headless(tmp_path, monkeypatch, capsys):
    _train_tiny(tmp_path)
    monkeypatch.setattr(
        "marl_distributedformation_tpu.utils.repo_root", lambda: tmp_path
    )
    import visualize_policy

    visualize_policy.main(
        ["name=viz", "headless=true", "steps=2", "num_agents_per_formation=3"]
    )
    out = capsys.readouterr().out
    assert "Loading model from" in out
    assert "rewards:" in out


def test_visualize_policy_no_checkpoint(tmp_path, monkeypatch):
    monkeypatch.setattr(
        "marl_distributedformation_tpu.utils.repo_root", lambda: tmp_path
    )
    import visualize_policy

    with pytest.raises(SystemExit, match="no rl_model"):
        visualize_policy.main(["name=nothere", "headless=true"])


def test_renderer_headless():
    from marl_distributedformation_tpu.compat.render import FormationRenderer

    params = EnvParams(num_agents=4, num_obstacles=2, obstacle_mode="fixed")
    r = FormationRenderer(params, title="t")
    agents = np.random.default_rng(0).uniform(0, 100, (4, 2))
    r.update(agents, np.array([200.0, 300.0]), np.array([[50.0, 200.0], [300.0, 400.0]]))
    r.draw()
    assert len(r.agent_circles) == 4 and len(r.obstacle_rects) == 2


def test_keyboard_move_constructs():
    """Teleop frontend builds its window and key handler headlessly (Agg)."""
    import keyboard_move

    keyboard_move.main(["num_agents=3"])  # plt.show returns under Agg


def test_obstacle_hits_matches_env_geometry():
    """The renderer's host-side containment mirror must agree with the
    env's jax `_in_obstacle` in both geometry modes (reduced per-obstacle
    vs per-agent, so cross-check through the any-collision scalar and a
    hand-built fixture)."""
    import jax.numpy as jnp

    from marl_distributedformation_tpu.compat.render import obstacle_hits
    from marl_distributedformation_tpu.env.formation import _in_obstacle

    rng = np.random.default_rng(7)
    for mode in ("parity", "fixed"):
        params = EnvParams(num_agents=6, num_obstacles=3, obstacle_mode=mode)
        for _ in range(20):
            agents = rng.uniform(0, 500, (6, 2))
            obstacles = rng.uniform(0, 500, (3, 2))
            hits = obstacle_hits(agents, obstacles, params)
            per_agent = np.asarray(
                _in_obstacle(jnp.asarray(agents), jnp.asarray(obstacles), params)
            )
            assert hits.any() == per_agent.any(), mode
    # Fixture: agent dead-center in obstacle 0 only.
    params = EnvParams(num_agents=2, num_obstacles=2, obstacle_mode="fixed")
    hits = obstacle_hits(
        np.array([[100.0, 100.0], [250.0, 250.0]]),
        np.array([[100.0, 100.0], [400.0, 400.0]]),
        params,
    )
    assert hits.tolist() == [True, False]
    # Parity geometry: point is the lower-left corner (SURVEY.md Q2), so an
    # agent just below/left of the point is NOT inside.
    params = EnvParams(num_agents=2, num_obstacles=1, obstacle_mode="parity")
    far = [250.0, 250.0]
    assert obstacle_hits(
        np.array([[99.0, 99.0], far]), np.array([[100.0, 100.0]]), params
    ).tolist() == [False]
    assert obstacle_hits(
        np.array([[101.0, 101.0], far]), np.array([[100.0, 100.0]]), params
    ).tolist() == [True]


def test_renderer_collision_recolor():
    """Obstacle rectangles flip red while an agent is inside and back to
    green when it leaves (reference simulate.py:101-106)."""
    import matplotlib

    from marl_distributedformation_tpu.compat.render import FormationRenderer

    params = EnvParams(num_agents=2, num_obstacles=2, obstacle_mode="fixed")
    r = FormationRenderer(params)
    obstacles = np.array([[100.0, 100.0], [400.0, 400.0]])
    goal = np.array([250.0, 250.0])
    red = matplotlib.colors.to_rgba("red")
    green = matplotlib.colors.to_rgba("green")

    r.update(np.array([[100.0, 100.0], [10.0, 10.0]]), goal, obstacles)
    assert r.obstacle_rects[0].get_facecolor() == red
    assert r.obstacle_rects[1].get_facecolor() == green

    r.update(np.array([[10.0, 10.0], [20.0, 20.0]]), goal, obstacles)
    assert r.obstacle_rects[0].get_facecolor() == green
    assert r.obstacle_rects[1].get_facecolor() == green


def test_simulate_obstacle_demo_headless(capsys):
    import simulate

    simulate.main(
        [
            "headless=true",
            "steps=30",
            "num_agents=4",
            "num_obstacles=4",
            "obstacle_mode=fixed",
            "seed=3",
        ]
    )
    out = capsys.readouterr().out
    assert "obstacle_hits=" in out


def test_metrics_logger_tensorboard(tmp_path):
    """use_tensorboard writes SB3-style event files (the reference's
    tensorboard_log capability, vectorized_env.py:129)."""
    pytest.importorskip("torch.utils.tensorboard")
    from marl_distributedformation_tpu.utils import MetricsLogger

    logger = MetricsLogger(tmp_path, use_tensorboard=True)
    logger.log({"reward": 1.5, "loss": 0.3}, step=100)
    logger.close()
    tb_dir = tmp_path / "tensorboard"
    assert any(
        f.name.startswith("events.out.tfevents") for f in tb_dir.iterdir()
    )


def test_loaded_policy_infers_nondefault_hidden(tmp_path):
    """Checkpoints trained with hidden_sizes != the 'MlpPolicy' default
    restore through playback/eval: LoadedPolicy infers the tower widths
    from the parameter shapes (the checkpoint records only the class
    name)."""
    import jax.numpy as jnp

    from marl_distributedformation_tpu.models import MLPActorCritic
    from marl_distributedformation_tpu.utils import save_checkpoint

    model = MLPActorCritic(act_dim=2, hidden=(32, 16))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    save_checkpoint(
        tmp_path, 10,
        {"policy": "MLPActorCritic", "params": params, "num_timesteps": 10},
    )
    pol = LoadedPolicy.from_checkpoint(latest_checkpoint(tmp_path))
    assert tuple(pol.model.hidden) == (32, 16)
    obs = np.zeros((4, 8), np.float32)
    actions, _ = pol.predict(obs, deterministic=True)
    mean, _, _ = model.apply(params, jnp.asarray(obs))
    np.testing.assert_allclose(
        actions, np.clip(np.asarray(mean), -1, 1), atol=1e-6
    )

    # Nested-actor models (PolicyHead under "actor"): the CTDE tower
    # widths infer through the nesting too.
    from marl_distributedformation_tpu.models import CTDEActorCritic
    from marl_distributedformation_tpu.utils import save_checkpoint as save2

    cmodel = CTDEActorCritic(act_dim=2, hidden=(24, 12))
    cparams = cmodel.init(jax.random.PRNGKey(1), jnp.zeros((1, 3, 8)))
    cdir = tmp_path / "ctde"
    save2(
        cdir, 10,
        {"policy": "CTDEActorCritic", "params": cparams,
         "num_timesteps": 10},
    )
    cpol = LoadedPolicy.from_checkpoint(
        latest_checkpoint(cdir), num_agents=3
    )
    assert tuple(cpol.model.hidden) == (24, 12)
    cobs = np.zeros((6, 8), np.float32)  # (M*N, obs) flat SB3 rows
    cacts, _ = cpol.predict(cobs, deterministic=True)
    assert cacts.shape == (6, 2)
