"""Smoke tests for the driver entry points (bench.py, __graft_entry__.py)."""

import pytest
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench as bench_mod
import __graft_entry__ as graft


def test_bench_runner_compiles_and_steps():
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.env.formation import reset_batch

    params = EnvParams(num_agents=bench_mod.N)
    state = reset_batch(jax.random.PRNGKey(0), params, 8)
    run_chunk = bench_mod.make_runner(params, m=8, chunk=4)
    state2, key, r = run_chunk(state, jax.random.PRNGKey(1))
    assert np.isfinite(float(r))
    assert not np.allclose(
        np.asarray(state2.agents), np.asarray(state.agents)
    )


@pytest.mark.slow
def test_bench_emits_parseable_json_on_cpu(monkeypatch, capsys):
    """The one-JSON-line contract must survive any backend state: force the
    CPU fallback path with tiny shapes and parse the output."""
    import json

    monkeypatch.setenv("BENCH_FORCE_CPU", "1")
    monkeypatch.setattr(bench_mod, "M", 8)
    monkeypatch.setattr(bench_mod, "CHUNK", 4)
    monkeypatch.setattr(bench_mod, "MIN_TIMED_S", 0.05)
    monkeypatch.setenv("BENCH_TRAIN_M", "4")
    monkeypatch.setenv("BENCH_KNN_M", "4")
    monkeypatch.setenv("BENCH_KNN_BIG_M", "2")
    monkeypatch.setenv("BENCH_KNN_BIG_N", "300")
    monkeypatch.setenv("BENCH_FUSED_CHUNKS", "1,2")  # tiny ladder for CI
    bench_mod.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys()
    assert rec["value"] > 0
    assert rec["train_env_steps_per_sec"] > 0
    assert rec["knn_env_steps_per_sec"] > 0
    assert rec["knn_big_env_steps_per_sec"] > 0  # phase 4 emits too
    # Scenario-engine phase (scenarios/): the 3-layer storm stack rate
    # rides the same JSON so the perf trajectory captures the wrapper
    # overhead.
    assert rec["scenario_env_steps_per_sec"] > 0
    assert rec["scenario_stack"] == "storm@1.0"
    # Anakin fused-scan phase: best-of-ladder rate, per-chunk rates, and
    # the compile-once RetraceGuard receipt (every fused program must
    # have compiled exactly once).
    assert rec["train_env_steps_per_sec_fused_scan"] > 0
    assert rec["train_fused_scan_chunk"] >= 1
    assert set(rec["train_fused_scan_compiles"].values()) == {1}
    assert rec["dispatch_overhead_pct"] >= 0.0
    assert "error" not in rec and "notes" not in rec
    # Provenance pin (VERDICT.md r3 weak #5): the parity field replays a
    # committed chip artifact, so it must carry the artifact's recorded
    # date — a CPU-fallback JSON must not read like same-run TPU parity.
    sentinels = (
        "no committed artifact",
        "no fused-kernel leg in artifact",
        "no big-kernel leg in artifact",
    )
    parity = rec["knn_device_parity"]
    if parity not in sentinels:
        assert parity.startswith("recorded 20"), parity
        assert "PARITY" in parity
        # Each phase's field replays the artifact leg for the kernel it
        # actually benchmarks: fused for knn (N=100), chunked for knn-big.
        assert "pallas_big" not in parity
    big = rec["knn_big_device_parity"]  # phase 4 always carries provenance
    if big not in sentinels:
        assert big.startswith("recorded 20"), big
        assert "pallas_big" in big or "PARITY_FAIL(big)" in big


@pytest.mark.slow
def test_fallback_json_carries_recorded_chip_story(monkeypatch, capsys):
    """A CPU-fallback line must point at the last real chip record with
    its date (VERDICT r3 weak #1) — not leave only CPU numbers beside a
    bare fallback flag."""
    import json

    monkeypatch.setattr(bench_mod, "probe_backend", lambda *a, **k: None)
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    for phase in ("TRAIN", "KNN", "KNN_BIG"):
        monkeypatch.setenv(f"BENCH_SKIP_{phase}", "1")
    monkeypatch.setattr(bench_mod, "M", 8)
    monkeypatch.setattr(bench_mod, "CHUNK", 4)
    monkeypatch.setattr(bench_mod, "MIN_TIMED_S", 0.05)
    bench_mod.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["fallback"] is True
    assert rec["recorded_chip_bench"].startswith("recorded ")
    # The pointer must reference the NEWEST committed chip record — it is
    # parsed from docs/acceptance/tpu_bench_r*.md at runtime, never a
    # string frozen at some round's numbers.
    assert "tpu_bench_r" in rec["recorded_chip_bench"]
    assert "formation-steps/s" in rec["recorded_chip_bench"]
    assert "unreachable" in rec["notes"]


def test_graft_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    mean, log_std, value = out
    assert mean.shape == (4096 * 5, 2)
    assert value.shape == (4096 * 5,)
    assert np.isfinite(np.asarray(mean)).all()


@pytest.mark.slow
def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_odd():
    graft.dryrun_multichip(1)
