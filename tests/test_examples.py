"""The examples/ scripts must actually run — they are the documented
extension surface (a custom flax model through ``Trainer(model=...)``)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.slow
def test_functional_env_example_runs():
    res = subprocess.run(
        [sys.executable, str(REPO / "examples" / "functional_env.py")],
        capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    # The example asserts convergence itself; pin its success line.
    assert "converged" in res.stdout


@pytest.mark.slow
def test_custom_policy_example_runs(tmp_path):
    env = dict(os.environ)
    env["EXAMPLE_TOTAL_TIMESTEPS"] = "16000"
    env["EXAMPLE_LOG_DIR"] = str(tmp_path / "logs")
    res = subprocess.run(
        [sys.executable, str(REPO / "examples" / "custom_policy.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "episode return/agent" in res.stdout
    # A return-quality threshold at this tiny budget would be flaky; pin
    # only the contract that both comparison numbers print and parse.
    line = [
        ln for ln in res.stdout.splitlines()
        if "episode return/agent" in ln
    ][0]
    assert "baseline" in line
    assert (tmp_path / "logs" / "metrics.jsonl").exists()
