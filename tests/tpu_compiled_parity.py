#!/usr/bin/env python
"""Compiled-mode Pallas k-NN parity check (VERDICT.md round-1 #5).

The pytest suite pins JAX to CPU (conftest.py), where the kernel only runs
in interpret mode — Mosaic lowering is never exercised there. This module
holds the single copy of the compiled-parity assertion:

- on hardware, run it directly: ``python tests/tpu_compiled_parity.py``
  (prints one PARITY_OK / PARITY_FAIL line), or run the whole suite with
  ``MDF_TPU_TESTS=1 pytest tests/`` (conftest leaves the real backend on and
  ``test_ops_pallas.py::test_compiled_pallas_parity_on_tpu`` calls
  :func:`run_parity`);
- bench.py's knn phase also exercises the compiled kernel on TPU
  (``impl="auto"`` selects it inside the jitted scan).
"""

import sys


def run_parity(m: int = 4096, n: int = 100, k: int = 4) -> str:
    """Assert compiled-pallas == xla at the north-star swarm shape; returns
    a human-readable OK message, raises AssertionError on mismatch."""
    import jax
    import numpy as np

    from marl_distributedformation_tpu.ops import knn_batch
    from marl_distributedformation_tpu.ops.knn_pallas import knn_batch_pallas

    pts = jax.random.uniform(jax.random.PRNGKey(0), (m, n, 2)) * 400.0
    idx_p, off_p, d_p = jax.block_until_ready(knn_batch_pallas(pts, k))
    idx_x, off_x, d_x = knn_batch(pts, k, impl="xla")
    np.testing.assert_array_equal(np.asarray(idx_p), np.asarray(idx_x))
    np.testing.assert_allclose(
        np.asarray(d_p), np.asarray(d_x), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(off_p), np.asarray(off_x), rtol=1e-4, atol=1e-4
    )
    return (
        f"compiled pallas == xla on {jax.devices()[0].device_kind} "
        f"(M={m}, N={n}, k={k})"
    )


def main() -> None:
    import jax

    if jax.default_backend() == "cpu":
        print("PARITY_SKIP: no accelerator backend", flush=True)
        return
    try:
        msg = run_parity()
    except AssertionError as e:
        print(f"PARITY_FAIL: {e}", flush=True)
        sys.exit(1)
    print(f"PARITY_OK: {msg}", flush=True)


if __name__ == "__main__":
    main()
