#!/usr/bin/env python
"""Compiled-mode Pallas k-NN parity check (VERDICT.md round-1 #5).

The pytest suite pins JAX to CPU (conftest.py), where the kernel only runs
in interpret mode — Mosaic lowering is never exercised there. This module
holds the single copy of the compiled-parity assertion:

- on hardware, run it directly: ``python tests/tpu_compiled_parity.py``
  (prints one PARITY_OK / PARITY_FAIL line), or run the whole suite with
  ``MDF_TPU_TESTS=1 pytest tests/`` (conftest leaves the real backend on and
  ``test_ops_pallas.py::test_compiled_pallas_parity_on_tpu`` runs all
  three legs);
- bench.py's knn phase also exercises the compiled kernel on TPU
  (``impl="auto"`` selects it inside the jitted scan).
"""

import sys
from pathlib import Path

# Standalone-invocation bootstrap: `python tests/tpu_compiled_parity.py`
# puts tests/ (not the repo root) on sys.path, and the package may not be
# pip-installed on a fresh machine — resolve the repo root explicitly so
# the documented command works from anywhere.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _assert_matches_xla(pallas_out, xla_out) -> None:
    """The shared leg assertion: exact index agreement, f32-tolerance
    distance/offset agreement, pallas vs the XLA search."""
    import numpy as np

    idx_p, off_p, d_p = pallas_out
    idx_x, off_x, d_x = xla_out
    np.testing.assert_array_equal(np.asarray(idx_p), np.asarray(idx_x))
    np.testing.assert_allclose(
        np.asarray(d_p), np.asarray(d_x), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(off_p), np.asarray(off_x), rtol=1e-4, atol=1e-4
    )


def run_parity(m: int = 4096, n: int = 100, k: int = 4) -> str:
    """Assert compiled-pallas == xla == host-float64 ground truth at the
    north-star swarm shape; returns a human-readable OK message, raises
    AssertionError on mismatch.

    The float64 leg is the absolute-correctness anchor (added round 3):
    round 2's matmul-expansion XLA path agreed with nothing — 33.5% of its
    neighbor indices were wrong on TPU (bf16 matmul cancellation at world
    scale) while the Pallas kernel was exact, so device-vs-device agreement
    alone is not sufficient evidence.
    """
    import jax
    import numpy as np

    from marl_distributedformation_tpu.ops import knn_batch
    from marl_distributedformation_tpu.ops.knn_pallas import knn_batch_pallas

    pts = jax.random.uniform(jax.random.PRNGKey(0), (m, n, 2)) * 400.0
    xla_out = knn_batch(pts, k, impl="xla")
    idx_x, _, d_x = xla_out
    _assert_matches_xla(
        jax.block_until_ready(knn_batch_pallas(pts, k)), xla_out
    )

    # Host float64 ground truth (vectorized; ~0.5 GB peak at the default
    # shape, fine for a hardware acceptance script).
    p64 = np.asarray(pts, np.float64)
    diff = p64[:, :, None, :] - p64[:, None, :, :]  # (M, N, N, 2)
    d2 = (diff * diff).sum(-1)
    mi = np.arange(n)
    d2[:, mi, mi] = np.inf
    idx_t = np.argsort(d2, axis=-1, kind="stable")[..., :k]
    d_t = np.sqrt(np.take_along_axis(d2, idx_t, axis=-1))
    frac_idx_wrong = (np.asarray(idx_x) != idx_t).mean()
    max_d_err = np.abs(np.asarray(d_x, np.float64) - d_t).max()
    # Ties at f32 granularity can legitimately flip an index; distances
    # must still match to f32 rounding. > 0.1% differing indices or any
    # distance off by > 1e-2 world units means a real precision defect.
    assert frac_idx_wrong < 1e-3, (
        f"device knn diverges from float64 truth: {frac_idx_wrong:.2%} "
        f"indices wrong, max |d| err {max_d_err:.3g}"
    )
    assert max_d_err < 1e-2, (
        f"device knn distances off by {max_d_err:.3g} world units vs "
        "float64 truth"
    )
    return (
        f"compiled pallas == xla == float64 truth on "
        f"{jax.devices()[0].device_kind} (M={m}, N={n}, k={k}; "
        f"idx mismatch vs f64 {frac_idx_wrong:.2e}, "
        f"max dist err {max_d_err:.2e})"
    )


def run_parity_mid(m: int = 256, n: int = 512, k: int = 4) -> str:
    """Compiled FUSED kernel at mid N (512 pads to 512 lanes, VMEM drives
    block_m to 2) vs the XLA search, on hardware. Pins the Mosaic sublane
    rule for sub-8 block_m blocks: a 2-D ``(block_m, n_pad)`` plane is not
    lowerable when block_m < 8, which interpret-mode CPU tests never see
    (the singleton-axis layout in ops/knn_pallas.py:_pad_planes is the
    fix; this leg is its hardware regression gate)."""
    import jax

    from marl_distributedformation_tpu.ops import knn_batch
    from marl_distributedformation_tpu.ops.knn_pallas import knn_batch_pallas

    pts = jax.random.uniform(jax.random.PRNGKey(2), (m, n, 2)) * 400.0
    _assert_matches_xla(
        jax.block_until_ready(knn_batch_pallas(pts, k)),
        knn_batch(pts, k, impl="xla"),
    )
    return (
        f"compiled pallas (block_m=2 sublane regime) == xla on "
        f"{jax.devices()[0].device_kind} (M={m}, N={n}, k={k})"
    )


def run_parity_big(m: int = 256, n: int = 1024, k: int = 4) -> str:
    """Compiled chunked-streaming kernel (ops/knn_pallas.py
    knn_batch_pallas_big — the path for swarms past the fused kernel's
    N <= 640 VMEM cliff) vs the XLA search, on hardware."""
    import jax

    from marl_distributedformation_tpu.ops import knn_batch
    from marl_distributedformation_tpu.ops.knn_pallas import (
        knn_batch_pallas_big,
    )

    pts = jax.random.uniform(jax.random.PRNGKey(1), (m, n, 2)) * 400.0
    _assert_matches_xla(
        jax.block_until_ready(knn_batch_pallas_big(pts, k)),
        knn_batch(pts, k, impl="xla"),
    )
    return (
        f"compiled pallas_big == xla on {jax.devices()[0].device_kind} "
        f"(M={m}, N={n}, k={k})"
    )


def main() -> None:
    import jax

    if jax.default_backend() == "cpu":
        print("PARITY_SKIP: no accelerator backend", flush=True)
        return
    # Catch Exception, not just AssertionError: the failure class this
    # gate exists for (Mosaic lowering rejections, e.g. the sublane rule)
    # surfaces as XlaRuntimeError/ValueError — those must still print a
    # PARITY_FAIL line for chip_checks.sh's grep, not a bare traceback.
    for leg, label in (
        (run_parity, ""),
        (run_parity_mid, "(mid)"),
        (run_parity_big, "(big)"),
    ):
        try:
            msg = leg()
        except Exception as e:  # noqa: BLE001 — report, don't die silently
            err = f"{type(e).__name__}: {e}" if not isinstance(
                e, AssertionError
            ) else str(e)
            print(f"PARITY_FAIL{label}: {err}"[:2000], flush=True)
            sys.exit(1)
        print(f"PARITY_OK: {msg}", flush=True)


if __name__ == "__main__":
    main()
