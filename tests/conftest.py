"""Test configuration: force JAX onto CPU with 8 virtual devices.

Must run before the first ``import jax`` anywhere in the test session so
mesh/sharding tests (SURVEY.md §4) can exercise multi-device code paths
without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell exports axon (TPU)
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
