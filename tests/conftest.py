"""Test configuration: force JAX onto CPU with 8 virtual devices.

The container's sitecustomize registers the axon TPU plugin and imports jax
at interpreter start, so setting ``JAX_PLATFORMS`` here is too late — use
``jax.config.update`` instead. ``XLA_FLAGS`` still must be set before the
first backend initialization for the 8 virtual CPU devices (SURVEY.md §4)
that mesh/sharding tests need.
"""

import os

os.environ.setdefault("MPLBACKEND", "Agg")  # headless matplotlib for frontends

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Persistent XLA compilation cache: the quick split's wall-clock is
# dominated by re-compiling near-identical jitted trainer programs across
# test files (VERDICT r4 next-#8). The cache is keyed on HLO + compile
# options, so correctness is unaffected; /tmp is wiped between driver
# sessions, which only costs the first run of a session.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# MDF_TPU_TESTS=1 leaves the real backend in place so the @skipif-cpu tests
# (compiled-mode Pallas parity) can actually run on hardware.
if os.environ.get("MDF_TPU_TESTS") != "1":
    jax.config.update("jax_platforms", "cpu")

    if len(jax.devices()) != 8:
        # The backend initialized before this conftest could set XLA_FLAGS
        # (e.g. `JAX_PLATFORMS=cpu pytest` under this image's sitecustomize,
        # which imports jax at interpreter start — round-1 VERDICT weak #5).
        # Re-provision the 8-device CPU mesh instead of failing every
        # sharding test.
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except RuntimeError:
            import jax.extend.backend as jeb

            jeb.clear_backends()
            jax.config.update("jax_num_cpu_devices", 8)
        assert len(jax.devices()) == 8, (
            f"could not provision the 8-device CPU test mesh "
            f"(have {len(jax.devices())})"
        )
