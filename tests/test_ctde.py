"""CTDE centralized-critic tests (BASELINE.json config 3).

Verifies the defining CTDE property — values are centralized (depend on the
whole formation) while actions stay decentralized (local obs only) — plus
mask semantics for padded formations and an end-to-end trainer smoke run at
20 agents.
"""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.models import CTDEActorCritic
from marl_distributedformation_tpu.train import TrainConfig, Trainer


def _init(model, n_agents, obs_dim, seed=0):
    obs = jax.random.normal(
        jax.random.PRNGKey(seed), (3, n_agents, obs_dim), jnp.float32
    )
    params = model.init(jax.random.PRNGKey(1), obs)
    return params, obs


def test_shapes_and_centralization():
    n, obs_dim = 20, 8
    model = CTDEActorCritic(act_dim=2)
    params, obs = _init(model, n, obs_dim)
    mean, log_std, value = model.apply(params, obs)
    assert mean.shape == (3, n, 2)
    assert log_std.shape == (2,)
    assert value.shape == (3, n)

    # Perturb only agent 7's observation in formation 0.
    perturbed = obs.at[0, 7].add(0.5)
    mean2, _, value2 = model.apply(params, perturbed)

    # Decentralized actor: other agents' action means are unchanged.
    np.testing.assert_allclose(
        np.delete(np.asarray(mean[0]), 7, axis=0),
        np.delete(np.asarray(mean2[0]), 7, axis=0),
        rtol=1e-6,
    )
    # Centralized critic: every agent's value in that formation changes.
    assert (np.abs(np.asarray(value2[0] - value[0])) > 1e-7).all()
    # Other formations are untouched (no cross-formation leakage).
    np.testing.assert_allclose(value[1:], value2[1:], rtol=1e-6)


def test_permutation_equivariance():
    n, obs_dim = 6, 8
    model = CTDEActorCritic(act_dim=2)
    params, obs = _init(model, n, obs_dim)
    perm = jnp.array([3, 1, 5, 0, 2, 4])
    _, _, value = model.apply(params, obs)
    _, _, value_p = model.apply(params, obs[:, perm])
    np.testing.assert_allclose(
        np.asarray(value[:, perm]), np.asarray(value_p), rtol=1e-5, atol=1e-6
    )


def test_mask_excludes_padded_agents():
    n, obs_dim = 8, 8
    model = CTDEActorCritic(act_dim=2)
    params, obs = _init(model, n, obs_dim)
    mask = jnp.ones((3, n)).at[:, 5:].set(0.0)

    _, _, value = model.apply(params, obs, mask)
    # Padded agents report value 0.
    assert (np.asarray(value[:, 5:]) == 0.0).all()

    # Changing a padded agent's obs must not change active agents' values.
    perturbed = obs.at[:, 6].add(10.0)
    _, _, value2 = model.apply(params, perturbed, mask)
    np.testing.assert_allclose(
        np.asarray(value[:, :5]), np.asarray(value2[:, :5]), rtol=1e-6
    )


@pytest.mark.slow
def test_trainer_ctde_20_agents():
    env_params = EnvParams(num_agents=20)
    ppo = PPOConfig(n_steps=4, n_epochs=2, batch_size=80)
    model = CTDEActorCritic(act_dim=env_params.act_dim)
    trainer = Trainer(
        env_params,
        ppo=ppo,
        config=TrainConfig(num_formations=4, checkpoint=False),
        model=model,
    )
    assert trainer.per_formation
    metrics = trainer.run_iteration()
    metrics = trainer.run_iteration()
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["reward"]))
