"""Tracing spine contract (obs/): ring bounds, exporters, flight
recorder, trace-ID hygiene.

The obs package is pure host-side bookkeeping (no jax import), so these
are fast unit tests:

- per-thread rings bound memory under sustained load — the tracer can
  stay wired into serving hot paths for months;
- a disabled tracer records nothing but still runs span bodies;
- Prometheus text exposition parses (``# TYPE`` lines, counter/gauge
  typing, ``replica{i}_*`` label folding, label-value escaping) and the
  content negotiation defaults to JSON;
- Chrome trace-event export is Perfetto-shaped (complete events, one
  lane per thread, trace IDs in ``args``) and ``scripts/trace_report.py``
  round-trips a ``Tracer.dump`` file, including ``--trace-id``
  filtering;
- the flight recorder dumps atomically, prunes to ``max_files``, and
  ``Tracer.incident`` never raises — even disabled, even with a broken
  ring.
"""

import json
import re
import sys
import threading
from pathlib import Path

from marl_distributedformation_tpu.obs import (
    FlightRecorder,
    Tracer,
    chrome_trace,
    configure,
    get_tracer,
    new_trace_id,
    prometheus_exposition,
    sanitize_trace_id,
    set_tracer,
    wants_prometheus,
)

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Tracer: recording, rings, clock anchor
# ---------------------------------------------------------------------------


def test_span_event_recording_and_snapshot_order():
    tr = Tracer(ring_size=64)
    with tr.span("outer", trace_id="t1", step=7):
        tr.event("inside", trace_id="t1")
    recs = tr.snapshot()
    # Oldest START first: the span OPENS before the inner event fires.
    assert [r["kind"] for r in recs] == ["span", "event"]
    span, event = recs
    assert event["name"] == "inside" and event["trace_id"] == "t1"
    assert span["name"] == "outer" and span["attrs"] == {"step": 7}
    assert span["duration_s"] >= 0.0
    # Monotonic endpoints were anchored onto the epoch clock.
    assert span["t0"] <= event["t0"] <= span["t1"]


def test_ring_bounds_memory_under_sustained_load():
    tr = Tracer(ring_size=32)

    def hammer():
        for i in range(50 * 32):
            tr.event("tick", i=i)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    hammer()  # main thread too
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = tr.snapshot()
    # Bounded: at most ring_size per recording thread (plus the bounded
    # retired-ring allowance if idents recycled mid-test), never the
    # 8000 records written per thread.
    assert len(recs) <= 32 * (5 + 8)
    # And the retained window is the NEWEST records.
    assert all(r["attrs"]["i"] >= 50 * 32 - 32 for r in recs)


def test_recycled_thread_ident_keeps_dead_threads_records():
    """CPython reuses a dead thread's ident; a later thread registering
    under it must not erase the dead thread's retained records — the
    whole point of a post-worker-death flight dump is reading exactly
    that history. Displaced rings retire into a bounded side buffer."""
    tr = Tracer(ring_size=16)

    def record_once(i):
        tr.event("worker", i=i)

    t = threading.Thread(target=record_once, args=(-1,))
    t.start()
    t.join()
    # Sequentially started threads near-always land on the recycled
    # ident; if they don't, the original entry survives untouched and
    # the assertions below hold trivially either way. 8 successors stay
    # within the retirement buffer, so every dead ring is retained.
    for i in range(8):
        t2 = threading.Thread(target=record_once, args=(i,))
        t2.start()
        t2.join()
    names = [r["attrs"]["i"] for r in tr.snapshot()]
    assert -1 in names and all(i in names for i in range(8))
    # Retirement stays bounded at the side buffer's maxlen rings —
    # unbounded thread churn cannot grow memory past it.
    for i in range(30):
        t3 = threading.Thread(target=record_once, args=(100 + i,))
        t3.start()
        t3.join()
    assert len(tr._retired) <= 8


def test_disabled_tracer_runs_body_but_records_nothing():
    tr = Tracer(enabled=False)
    ran = []
    with tr.span("s"):
        ran.append(True)
    tr.event("e")
    tr.add_span("a", 0.0, 1.0)
    assert ran == [True]
    assert tr.snapshot() == []


def test_add_span_backdated_via_epoch_anchor():
    tr = Tracer()
    epoch_start = tr.epoch_anchor - 10.0  # "10 seconds before init"
    tr.add_span(
        "backdated",
        tr.epoch_to_mono(epoch_start),
        tr.epoch_to_mono(epoch_start + 2.5),
        trace_id="t",
    )
    (rec,) = tr.snapshot()
    assert abs(rec["t0"] - epoch_start) < 1e-6
    assert abs(rec["duration_s"] - 2.5) < 1e-6


def test_trace_id_hygiene():
    assert len(new_trace_id()) == 16
    assert new_trace_id() != new_trace_id()
    assert sanitize_trace_id("  abc-DEF_1.2  ") == "abc-DEF_1.2"
    assert sanitize_trace_id(None) is None
    assert sanitize_trace_id("") is None
    assert sanitize_trace_id('bad"quote') is None
    assert sanitize_trace_id("new\nline") is None
    # non-ASCII Unicode alphanumerics pass str.isalnum() but are not
    # URL/log/filename-safe — must be rejected (caller re-mints)
    assert sanitize_trace_id("µé¹abc") is None
    long = sanitize_trace_id("a" * 200)
    assert long == "a" * 64  # length-bounded, not rejected


def test_global_registry_configure_and_swap():
    original = get_tracer()
    private = Tracer(ring_size=8)
    try:
        assert set_tracer(private) is original
        assert get_tracer() is private
        configure(enabled=False, ring_size=4)
        assert private.enabled is False and private.ring_size == 4
    finally:
        set_tracer(original)
    assert get_tracer() is original


# ---------------------------------------------------------------------------
# Chrome trace export + scripts/trace_report.py
# ---------------------------------------------------------------------------


def test_chrome_trace_shape_and_malformed_record_tolerance():
    tr = Tracer()
    with tr.span("work", trace_id="abc"):
        pass
    tr.event("mark")
    records = tr.snapshot() + [{"garbage": True}, "not even a dict"]
    trace = chrome_trace(records, process_name="unit")
    events = trace["traceEvents"]
    complete = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    meta = [e for e in events if e.get("ph") == "M"]
    assert len(complete) == 1 and len(instants) == 1
    assert complete[0]["args"]["trace_id"] == "abc"
    assert complete[0]["dur"] >= 0.0
    # One lane per thread, named via metadata.
    names = {e["name"] for e in meta}
    assert {"process_name", "thread_name"} <= names
    # JSON-serializable end to end (what the viewer actually loads).
    json.dumps(trace)


def test_trace_report_renders_dump_and_filters_by_trace_id(tmp_path):
    tr = Tracer()
    keep = new_trace_id()
    with tr.span("promotion.gate_eval", trace_id=keep):
        pass
    with tr.span("serve.batch", trace_id="other"):
        pass
    tr.event("unlabelled")
    dump = tr.dump(tmp_path / "trace_spans.json")
    assert json.loads(dump.read_text())["format"] == "marl-obs-spans"

    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    out = tmp_path / "all.chrome.json"
    assert trace_report.main([str(dump), "--out", str(out)]) == 0
    trace = json.loads(out.read_text())
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert {s["name"] for s in spans} == {
        "promotion.gate_eval", "serve.batch",
    }
    # --trace-id pulls one promotion's lane out of the run.
    filtered = tmp_path / "one.chrome.json"
    assert (
        trace_report.main(
            [str(dump), "--trace-id", keep, "--out", str(filtered)]
        )
        == 0
    )
    spans = [
        e
        for e in json.loads(filtered.read_text())["traceEvents"]
        if e.get("ph") in ("X", "i")
    ]
    assert [s["name"] for s in spans] == ["promotion.gate_eval"]


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

# One exposition line: name{labels} value — the grammar a scraper needs.
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (NaN|[+-]?Inf|[-+0-9.e]+)$"
)


def test_prometheus_exposition_parses():
    text = prometheus_exposition(
        {
            "routed_total": 42,
            "queue_depth": 3.5,
            "replica0_occupancy": 0.25,
            "replica1_occupancy": 0.75,
            "annotation": "not-a-number",  # skipped, not fatal
        },
        labels={"run": 'we"ird\nname\\x'},
    )
    lines = text.strip().splitlines()
    types = {
        line.split()[2]: line.split()[3]
        for line in lines
        if line.startswith("# TYPE")
    }
    # _total keys are counters, the rest gauges.
    assert types["marl_routed_total"] == "counter"
    assert types["marl_queue_depth"] == "gauge"
    assert types["marl_occupancy"] == "gauge"
    samples = [line for line in lines if not line.startswith("#")]
    for line in samples:
        assert _PROM_LINE.match(line), f"unparseable sample: {line!r}"
    # replica{i}_* folded into ONE family with a replica label.
    occ = [line for line in samples if line.startswith("marl_occupancy")]
    assert len(occ) == 2
    assert any('replica="0"' in line for line in occ)
    assert any('replica="1"' in line for line in occ)
    # Label escaping per the exposition spec.
    assert 'run="we\\"ird\\nname\\\\x"' in occ[0]
    # The non-numeric annotation was dropped, not rendered.
    assert not any("annotation" in line for line in lines)


def test_prometheus_content_negotiation():
    assert not wants_prometheus(None)
    assert not wants_prometheus("")
    assert not wants_prometheus("application/json")
    assert not wants_prometheus("*/*")
    assert wants_prometheus("text/plain")
    assert wants_prometheus("text/plain; version=0.0.4")
    assert wants_prometheus("application/openmetrics-text")
    # compound headers negotiate by q-value/preference, not substring:
    # a JSON client listing text/plain as a fallback keeps JSON
    assert not wants_prometheus("application/json, text/plain, */*")
    assert not wants_prometheus("text/plain;q=0")
    assert wants_prometheus("application/json;q=0.2, text/plain;q=0.8")
    assert wants_prometheus("text/plain, application/json;q=0.5")


# ---------------------------------------------------------------------------
# Flight recorder + incidents
# ---------------------------------------------------------------------------


def test_flight_recorder_dumps_prunes_and_survives_failure(tmp_path):
    rec = FlightRecorder(tmp_path / "fr", last_n=4, max_files=3)
    tr = Tracer(ring_size=16, flightrec=rec)
    for i in range(10):
        tr.event("tick", i=i)
    for k in range(5):
        path = tr.incident("circuit_break", replica=k)
        assert path is not None and path.exists()
    dumps = rec.dumps()
    assert len(dumps) == 3  # pruned to max_files, oldest gone
    payload = json.loads(dumps[-1].read_text())
    assert payload["trigger"] == "circuit_break"
    assert payload["context"] == {"replica": 4}
    assert 0 < len(payload["records"]) <= 4  # the last-N window
    # No torn dot-tmp files left behind.
    assert not list((tmp_path / "fr").glob(".*tmp"))
    assert tr.incidents_total == 5


def test_incident_dumps_context_even_when_tracing_disabled(tmp_path):
    rec = FlightRecorder(tmp_path / "fr", last_n=8)
    tr = Tracer(enabled=False, flightrec=rec)
    path = tr.incident("rollback_trip", trace_id="t9", from_step=300)
    assert path is not None
    payload = json.loads(path.read_text())
    assert payload["trace_id"] == "t9"
    assert payload["context"]["from_step"] == 300
    assert payload["records"] == []  # disabled ring is empty; context lands


def test_incident_never_raises():
    class BrokenRecorder:
        def dump(self, *a, **k):
            raise OSError("disk full")

    tr = Tracer(flightrec=BrokenRecorder())
    assert tr.incident("scheduler_worker_death", error="boom") is None
    # No recorder attached at all: still fine, still counted.
    bare = Tracer()
    assert bare.incident("wedged_barrier_abort") is None
    assert bare.incidents_total == 1
