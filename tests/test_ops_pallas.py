"""Fused Pallas k-NN kernel vs the XLA reference path.

Runs the kernel in interpret mode (CPU, conftest.py) and checks it
reproduces ``ops.knn.knn``'s selection, ordering, masking, and self-loop
semantics exactly. On real TPU hardware the same kernel compiles natively
(``impl="pallas"``); these tests pin its semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.formation import (
    compute_obs,
    reset_batch,
    step_batch,
)
from marl_distributedformation_tpu.ops import knn, knn_batch
from marl_distributedformation_tpu.ops.knn_pallas import knn_batch_pallas


def _xla_batch(points, k, valid=None):
    if valid is None:
        return jax.vmap(lambda p: knn(p, k))(points)
    return jax.vmap(lambda p, v: knn(p, k, v))(points, valid)


def _assert_matches(pallas_out, xla_out):
    idx_p, off_p, dist_p = pallas_out
    idx_x, off_x, dist_x = xla_out
    np.testing.assert_array_equal(np.asarray(idx_p), np.asarray(idx_x))
    np.testing.assert_allclose(
        np.asarray(off_p), np.asarray(off_x), rtol=1e-5, atol=1e-5
    )
    # Both sides now compute direct coordinate differences (the round-3
    # precision fix removed the |a|^2+|b|^2-2ab expansion from the XLA
    # path); the loose atol predates that fix and is kept for headroom.
    np.testing.assert_allclose(
        np.asarray(dist_p), np.asarray(dist_x), rtol=1e-3, atol=2e-2
    )


@pytest.mark.parametrize(
    "m,n,k", [(4, 100, 8), (3, 10, 3), (2, 130, 4), (1, 5, 2)]
)
def test_matches_xla_path(m, n, k):
    pts = jax.random.uniform(
        jax.random.PRNGKey(m * 1000 + n), (m, n, 2), minval=0.0, maxval=400.0
    )
    _assert_matches(
        knn_batch_pallas(pts, k, interpret=True), _xla_batch(pts, k)
    )


def test_matches_xla_path_with_valid_mask():
    m, n, k = 4, 20, 5
    pts = jax.random.uniform(
        jax.random.PRNGKey(7), (m, n, 2), minval=0.0, maxval=400.0
    )
    # Mix of rows with plenty of neighbors and rows short enough (<= k
    # valid agents) to force self-loop degradation.
    n_valid = jnp.array([20, 12, 5, 3])
    valid = jnp.arange(n)[None, :] < n_valid[:, None]
    _assert_matches(
        knn_batch_pallas(pts, k, valid=valid, interpret=True),
        _xla_batch(pts, k, valid=valid),
    )


def test_ascending_distance_order():
    pts = jax.random.uniform(jax.random.PRNGKey(3), (2, 50, 2)) * 100.0
    _, _, dists = knn_batch_pallas(pts, 6, interpret=True)
    d = np.asarray(dists)
    assert (np.diff(d, axis=-1) >= -1e-6).all()


def test_vmem_guard_rejects_oversized_n():
    from marl_distributedformation_tpu.ops.knn_pallas import fits_vmem

    assert fits_vmem(512) and not fits_vmem(1000)
    pts = jnp.zeros((1, 1000, 2))
    with pytest.raises(ValueError, match="VMEM"):
        knn_batch_pallas(pts, 4, interpret=True)
    # auto dispatch must quietly take the XLA path instead of exploding
    idx, _, _ = knn_batch(
        jax.random.uniform(jax.random.PRNGKey(0), (1, 1000, 2)), 4,
        impl="auto",
    )
    assert idx.shape == (1, 1000, 4)


def test_knn_batch_dispatch():
    pts = jax.random.uniform(jax.random.PRNGKey(11), (2, 30, 2)) * 50.0
    _assert_matches(
        knn_batch(pts, 4, impl="pallas_interpret"),
        knn_batch(pts, 4, impl="xla"),
    )
    with pytest.raises(AssertionError):
        knn_batch(pts, 4, impl="bogus")


@pytest.mark.slow
def test_step_batch_obs_identical_across_impls():
    """The full env step must produce identical knn observations whether the
    neighbor search runs through XLA or the Pallas kernel."""
    base = EnvParams(num_agents=16, obs_mode="knn", knn_k=4)
    key = jax.random.PRNGKey(0)
    state = reset_batch(key, base, 6)
    vel = (
        jax.random.uniform(jax.random.PRNGKey(1), (6, 16, 2)) * 2.0 - 1.0
    ) * base.max_speed

    outs = {}
    for impl in ("xla", "pallas_interpret"):
        params = base.replace(knn_impl=impl)
        next_state, tr = step_batch(state, vel, params)
        outs[impl] = (np.asarray(tr.obs), np.asarray(tr.reward))
    np.testing.assert_allclose(
        outs["xla"][0], outs["pallas_interpret"][0], rtol=1e-5, atol=1e-5
    )
    np.testing.assert_array_equal(outs["xla"][1], outs["pallas_interpret"][1])


def test_reset_obs_batch_path():
    """Batched compute_obs (ndim == 3) agrees with the per-formation path."""
    params = EnvParams(num_agents=12, obs_mode="knn", knn_k=3)
    state = reset_batch(jax.random.PRNGKey(5), params, 4)
    batched = compute_obs(state.agents, state.goal, params)
    single = jnp.stack(
        [
            compute_obs(state.agents[i], state.goal[i], params)
            for i in range(4)
        ]
    )
    np.testing.assert_allclose(
        np.asarray(batched), np.asarray(single), rtol=1e-6, atol=1e-6
    )


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="compiled-mode Pallas needs a real TPU backend — run "
    "`MDF_TPU_TESTS=1 pytest` (conftest opt-out) or "
    "`python tests/tpu_compiled_parity.py` on hardware (VERDICT.md "
    "round-1 #5)",
)
def test_compiled_pallas_parity_on_tpu():
    """All three hardware legs: the north-star shape (fused, block_m=8),
    the mid-N sublane regime (fused, block_m=2 — the Mosaic (8, 128) rule
    regression gate for the singleton-axis plane layout), and the chunked
    big-N kernel. Interpret mode (the CPU tests above) does not exercise
    Mosaic lowering; this does. Single source of truth for the assertions:
    tests/tpu_compiled_parity.py."""
    from tpu_compiled_parity import run_parity, run_parity_big, run_parity_mid

    run_parity()
    run_parity_mid()
    run_parity_big()


def test_auto_dispatch_consults_spmd_guard(monkeypatch):
    """With the backend pinned to 'tpu', the auto dispatch must pick xla for
    partitioner-controlled batches and pallas for local ones — guarding the
    round-1 ADVICE-high regression at the dispatch level."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    import importlib

    # ops/__init__ rebinds the name `knn` to the function, so attribute-style
    # module imports resolve to it; go through the module registry instead.
    knn_mod = importlib.import_module(
        "marl_distributedformation_tpu.ops.knn"
    )
    from marl_distributedformation_tpu.parallel import make_mesh

    monkeypatch.setattr(
        knn_mod.jax, "default_backend", lambda: "tpu"
    )
    pts = jnp.zeros((16, 12, 2))
    assert knn_mod._resolve_auto_impl(pts) == "pallas"
    mesh = make_mesh({"dp": 8})
    pts_dp = jax.device_put(pts, NamedSharding(mesh, P("dp")))
    assert knn_mod._resolve_auto_impl(pts_dp) == "xla"
    seen = []
    jax.jit(
        lambda p: seen.append(knn_mod._resolve_auto_impl(p)) or p
    )(pts_dp)
    assert seen[-1] == "xla"
    # Over the fused kernel's VMEM budget -> the chunked streaming kernel
    # (round 3); the SPMD guard still applies to it.
    big = jnp.zeros((16, 4096, 2))
    assert knn_mod._resolve_auto_impl(big) == "pallas_big"
    big_dp = jax.device_put(big, NamedSharding(mesh, P("dp")))
    assert knn_mod._resolve_auto_impl(big_dp) == "xla"


def test_xla_knn_precision():
    """Regression pin for the round-2 TPU correctness bug (VERDICT.md r2
    Weak #1): pairwise_sq_dists must NOT lower to a matmul. The old
    |a|^2+|b|^2-2a.b expansion ran the cross term through dot_general,
    which TPUs execute at bf16 input precision by default — at coordinate
    scale ~400 that corrupted 33% of neighbor indices on the chip. The
    direct broadcast form has no dot at all, so the bug class is
    structurally excluded; additionally check f64-level accuracy at the
    world-coordinate scale where the old form lost precision even in f32.
    """
    from marl_distributedformation_tpu.ops.knn import pairwise_sq_dists

    pts = jnp.asarray(
        np.random.default_rng(0).uniform(0, 400, (100, 2)), jnp.float32
    )
    jaxpr = jax.make_jaxpr(pairwise_sq_dists)(pts)
    prims = {eqn.primitive.name for eqn in jaxpr.jaxpr.eqns}
    assert "dot_general" not in prims, (
        "pairwise_sq_dists lowered to a matmul — on TPU this runs at bf16 "
        "input precision and corrupts the neighbor graph at world scale"
    )

    d2 = np.asarray(pairwise_sq_dists(pts), np.float64)
    p64 = np.asarray(pts, np.float64)
    ref = ((p64[:, None, :] - p64[None, :, :]) ** 2).sum(-1)
    ref[np.diag_indices(100)] += 1e12
    off_diag = ~np.eye(100, dtype=bool)
    np.testing.assert_allclose(
        d2[off_diag], ref[off_diag], rtol=1e-5, atol=1e-2
    )


class TestChunkedBigKernel:
    """knn_batch_pallas_big: the streaming kernel for N past the fused
    kernel's VMEM cliff. Interpret mode with small tiles exercises the
    multi-chunk / multi-row-block merge paths on CPU."""

    def _run(self, m, n, k, block_r=128, chunk_c=128, valid=None, seed=0):
        from marl_distributedformation_tpu.ops.knn_pallas import (
            knn_batch_pallas_big,
        )

        pts = jnp.asarray(
            np.random.default_rng(seed).uniform(0, 400, (m, n, 2)),
            jnp.float32,
        )
        got = knn_batch_pallas_big(
            pts, k, valid, block_r=block_r, chunk_c=chunk_c, interpret=True
        )
        want = knn_batch(pts, k, valid, impl="xla")
        return got, want

    @pytest.mark.parametrize(
        "m,n,k,block_r,chunk_c",
        [
            # Fast split keeps one multi-chunk and one spill case; the
            # heavier interpret-mode shapes are slow-marked (full suite +
            # the hardware gate tests/tpu_compiled_parity.py cover them).
            (3, 300, 4, 128, 128),   # 3 chunks, 3 row blocks, ragged N
            pytest.param(
                2, 700, 4, 128, 256, marks=pytest.mark.slow
            ),                       # past the fused kernel's cliff
            (1, 129, 3, 128, 128),   # barely spills into chunk 2
            pytest.param(
                4, 256, 5, 128, 128, marks=pytest.mark.slow
            ),                       # k > 4
        ],
    )
    def test_matches_xla(self, m, n, k, block_r, chunk_c):
        (gi, go, gd), (wi, wo, wd) = self._run(
            m, n, k, block_r=block_r, chunk_c=chunk_c
        )
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(wi))
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(wd), rtol=1e-6, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(go), np.asarray(wo), rtol=1e-6, atol=1e-6
        )

    @pytest.mark.slow
    def test_valid_mask_and_self_loops(self):
        """Invalid points are never selected; short rows degrade to
        self-loops exactly like ops.knn.knn's valid path."""
        rng = np.random.default_rng(5)
        valid = jnp.asarray(rng.random((3, 300)) > 0.5)
        (gi, go, gd), (wi, wo, wd) = self._run(3, 300, 4, valid=valid)
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(wi))
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(wd), rtol=1e-6, atol=1e-6
        )

    @pytest.mark.slow
    def test_tie_breaking_matches_top_k(self):
        """Duplicate coordinates force distance ties; selection must match
        lax.top_k's stable lower-index preference bit-for-bit."""
        from marl_distributedformation_tpu.ops.knn_pallas import (
            knn_batch_pallas_big,
        )

        base = np.random.default_rng(9).uniform(0, 400, (2, 40, 2))
        pts = np.tile(base, (1, 8, 1))  # every point duplicated 8x -> 320
        pts = jnp.asarray(pts, jnp.float32)
        gi, _, gd = knn_batch_pallas_big(
            pts, 4, block_r=128, chunk_c=128, interpret=True
        )
        wi, _, wd = knn_batch(pts, 4, impl="xla")
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(wi))
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(wd), rtol=1e-6, atol=1e-6
        )

    def test_auto_dispatch_selects_big_kernel(self, monkeypatch):
        import importlib

        knn_mod = importlib.import_module(
            "marl_distributedformation_tpu.ops.knn"
        )
        monkeypatch.setattr(knn_mod.jax, "default_backend", lambda: "tpu")
        assert knn_mod._resolve_auto_impl(jnp.zeros((4, 100, 2))) == "pallas"
        assert (
            knn_mod._resolve_auto_impl(jnp.zeros((4, 641, 2)))
            == "pallas_big"
        )
        assert (
            knn_mod._resolve_auto_impl(jnp.zeros((4, 4096, 2)))
            == "pallas_big"
        )
        # Past the compile-time cap (static chunk unroll), auto falls back.
        assert (
            knn_mod._resolve_auto_impl(jnp.zeros((1, 20000, 2))) == "xla"
        )


    @pytest.mark.slow
    def test_displaced_tie_keeps_top_k_order(self):
        """Regression for the bubble-insert tie bug: a best list holding
        two equal-distance neighbors (lower column first) must keep that
        order when a CLOSER candidate from a later chunk displaces the
        list — a strict '<' insert would trap the displaced lower-column
        element behind its equal."""
        from marl_distributedformation_tpu.ops.knn_pallas import (
            knn_batch_pallas_big,
        )

        n = 300
        pts = np.full((1, n, 2), 1e4, np.float32)
        pts[0, 0] = (0.0, 0.0)       # query
        pts[0, 5] = (10.0, 0.0)      # tie A (dist 10), chunk 0
        pts[0, 9] = (0.0, 10.0)      # tie B (dist 10), chunk 0
        pts[0, 200] = (1.0, 0.0)     # closer, chunk 1 -> displaces
        pts = jnp.asarray(pts)
        gi, _, gd = knn_batch_pallas_big(
            pts, 3, block_r=128, chunk_c=128, interpret=True
        )
        wi, _, wd = knn_batch(pts, 3, impl="xla")
        assert wi[0, 0].tolist() == [200, 5, 9]  # top_k stable order
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(wi))
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(wd), rtol=1e-6, atol=1e-6
        )
