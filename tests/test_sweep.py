"""Seed-sweep population training (train/sweep.py).

The load-bearing invariant: sweep member i is bit-compatible with a
single Trainer constructed at seed+i — a sweep IS K reference-parity
runs, fused into one program. Plus: seed-axis mesh sharding changes
nothing numerically, and per-member checkpoints flow through the
standard playback/resume tooling.
"""

import json
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from marl_distributedformation_tpu.algo import PPOConfig  # noqa: E402
from marl_distributedformation_tpu.env import EnvParams  # noqa: E402
from marl_distributedformation_tpu.parallel import make_mesh  # noqa: E402
from marl_distributedformation_tpu.train import (  # noqa: E402
    SweepTrainer,
    TrainConfig,
    Trainer,
)

PPO = PPOConfig(n_steps=4, batch_size=24, n_epochs=2)


def _cfg(tmp_path, **kw):
    base = dict(
        num_formations=4,
        seed=0,
        checkpoint=False,
        name="sweep",
        log_dir=str(tmp_path / "logs"),
    )
    base.update(kw)
    return TrainConfig(**base)


def _leaves_allclose(a, b, rtol=1e-5, atol=1e-6):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol
        )


@pytest.mark.slow
def test_member_matches_single_trainer(tmp_path):
    """Member i of a K=2 sweep == Trainer(seed=i), params and metrics."""
    params = EnvParams(num_agents=3)
    sweep = SweepTrainer(
        params, ppo=PPO, config=_cfg(tmp_path), num_seeds=2
    )
    singles = [
        Trainer(params, ppo=PPO, config=_cfg(tmp_path, seed=i))
        for i in range(2)
    ]
    for _ in range(2):
        sweep_metrics = sweep.run_iteration()
        single_metrics = [t.run_iteration() for t in singles]
    for i, t in enumerate(singles):
        _leaves_allclose(
            jax.tree_util.tree_map(
                lambda x: x[i], sweep.train_state.params
            ),
            t.train_state.params,
        )
        np.testing.assert_allclose(
            float(sweep_metrics["reward"][i]),
            float(single_metrics[i]["reward"]),
            rtol=1e-5,
        )
    # Distinct seeds actually diverge.
    assert not np.allclose(
        np.asarray(sweep_metrics["reward"][0]),
        np.asarray(sweep_metrics["reward"][1]),
    )


@pytest.mark.slow
def test_seed_axis_sharding_matches_unsharded(tmp_path):
    """mesh={dp: 4} shards the population with no effect beyond fp
    reduction-order noise.

    Tolerances are the explicit Adam-amplification budget
    (tests/adam_budget.py): the one-device and dp-sharded XLA lowerings
    reduce in different orders (~3e-8 per minibatch gradient), and
    Adam's normalized update amplifies any tie-break to O(lr) per
    optimizer step — a flat rtol can never gate this correctly."""
    from adam_budget import adam_parity_atol, trajectory_rtol, updates_per_run

    params = EnvParams(num_agents=3)
    plain = SweepTrainer(params, ppo=PPO, config=_cfg(tmp_path), num_seeds=4)
    sharded = SweepTrainer(
        params,
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=4,
        mesh=make_mesh({"dp": 4}),
    )
    iterations = 2
    for _ in range(iterations):
        m_plain = plain.run_iteration()
        m_shard = sharded.run_iteration()
    # Per-member rollout rows: n_steps * num_formations * num_agents.
    updates = updates_per_run(PPO, PPO.n_steps * 4 * 3, iterations)
    _leaves_allclose(
        plain.train_state.params,
        sharded.train_state.params,
        rtol=0,
        atol=adam_parity_atol(PPO.learning_rate, updates),
    )
    np.testing.assert_allclose(
        np.asarray(m_plain["reward"]),
        np.asarray(m_shard["reward"]),
        rtol=trajectory_rtol(PPO.learning_rate, updates),
    )


def test_sweep_rejects_bad_population_split(tmp_path):
    with pytest.raises(AssertionError, match="divisible"):
        SweepTrainer(
            EnvParams(num_agents=3),
            ppo=PPO,
            config=_cfg(tmp_path),
            num_seeds=3,
            mesh=make_mesh({"dp": 4}),
        )
    with pytest.raises(AssertionError, match="'dp'"):
        SweepTrainer(
            EnvParams(num_agents=3),
            ppo=PPO,
            config=_cfg(tmp_path),
            num_seeds=4,
            mesh=make_mesh({"dp": 2, "sp": 2}),
        )


@pytest.mark.slow
def test_lr_sweep_on_mesh(tmp_path):
    """Per-member rates (inject_hyperparams state) under the seed-axis
    shard_map: the rate array shards with the rest of the population."""
    sweep = SweepTrainer(
        EnvParams(num_agents=3),
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=4,
        mesh=make_mesh({"dp": 4}),
        learning_rates=[1e-4, 1e-3, 3e-3, 1e-2],
    )
    metrics = sweep.run_iteration()
    assert np.isfinite(np.asarray(metrics["loss"])).all()


@pytest.mark.slow
def test_knn_sweep_on_mesh(tmp_path):
    """knn observations inside a seed-sharded sweep: the shard_map wrap
    keeps the per-device neighbor search local (the SPMD partitioner never
    sees it), so this must compile and run."""
    sweep = SweepTrainer(
        EnvParams(num_agents=6, obs_mode="knn", knn_k=2),
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=4,
        mesh=make_mesh({"dp": 4}),
    )
    metrics = sweep.run_iteration()
    assert np.isfinite(np.asarray(metrics["reward"])).all()


@pytest.mark.slow
def test_lr_sweep_members_train_at_their_own_rate(tmp_path):
    """Per-member learning rates: lr=0 freezes that member, a nonzero-lr
    member matches a single Trainer run at that rate (the inject_hyperparams
    wrapper must be numerically equivalent to plain adam)."""
    import dataclasses

    params = EnvParams(num_agents=3)
    sweep = SweepTrainer(
        params,
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=2,
        learning_rates=[0.0, PPO.learning_rate],
    )
    frozen_before = jax.tree_util.tree_map(
        lambda x: np.asarray(x[0]).copy(), sweep.train_state.params
    )
    sweep.run_iteration()
    _leaves_allclose(
        jax.tree_util.tree_map(lambda x: x[0], sweep.train_state.params),
        frozen_before,
        rtol=0,
        atol=0,
    )

    single = Trainer(params, ppo=PPO, config=_cfg(tmp_path, seed=1))
    single.run_iteration()
    _leaves_allclose(
        jax.tree_util.tree_map(lambda x: x[1], sweep.train_state.params),
        single.train_state.params,
    )

    # Distinct nonzero rates diverge.
    sweep2 = SweepTrainer(
        params,
        ppo=dataclasses.replace(PPO),
        config=_cfg(tmp_path),
        num_seeds=2,
        learning_rates=[1e-4, 1e-2],
    )
    for _ in range(2):
        m = sweep2.run_iteration()
    assert not np.allclose(
        np.asarray(m["loss"][0]), np.asarray(m["loss"][1])
    )

    with pytest.raises(AssertionError, match="one entry per member"):
        SweepTrainer(
            params, ppo=PPO, config=_cfg(tmp_path), num_seeds=2,
            learning_rates=[1e-3],
        )


@pytest.mark.slow
def test_lr_sweep_member_checkpoint_resumes_params_only(tmp_path):
    """lr-sweep member checkpoints omit the inject-wrapped opt_state and
    still warm-start a single Trainer (fresh Adam moments)."""
    params = EnvParams(num_agents=3)
    cfg = _cfg(
        tmp_path,
        checkpoint=True,
        total_timesteps=PPO.n_steps * 4 * 3,  # 1 iteration
    )
    sweep = SweepTrainer(
        params, ppo=PPO, config=cfg, num_seeds=2,
        learning_rates=[1e-3, 1e-2],
    )
    sweep.train()
    summary = json.loads(
        (Path(sweep.log_dir) / "sweep_summary.json").read_text()
    )
    np.testing.assert_allclose(
        summary["learning_rates"], [1e-3, 1e-2], rtol=1e-6
    )

    member_dir = Path(sweep.log_dir) / "seed0"
    resumed = Trainer(
        params,
        ppo=PPO,
        config=_cfg(
            tmp_path, log_dir=str(member_dir), resume=True, checkpoint=False
        ),
    )
    assert resumed.num_timesteps == sweep.num_timesteps
    _leaves_allclose(
        resumed.train_state.params,
        jax.tree_util.tree_map(lambda x: x[0], sweep.train_state.params),
    )


@pytest.mark.slow
def test_resume_warns_on_learning_rate_mismatch(tmp_path, capsys):
    """A member trained at a non-default rate must warn when resumed at
    a different one (the rate is recorded in the checkpoint)."""
    params = EnvParams(num_agents=3)
    cfg = _cfg(
        tmp_path,
        checkpoint=True,
        total_timesteps=PPO.n_steps * 4 * 3,  # 1 iteration
    )
    sweep = SweepTrainer(
        params, ppo=PPO, config=cfg, num_seeds=2,
        learning_rates=[1e-3, 1e-2],
    )
    sweep.train()
    capsys.readouterr()
    Trainer(
        params,
        ppo=PPO,  # learning_rate=1e-3 != seed1's 1e-2
        config=_cfg(
            tmp_path,
            log_dir=str(Path(sweep.log_dir) / "seed1"),
            resume=True,
            checkpoint=False,
        ),
    )
    assert "learning_rate=0.01" in capsys.readouterr().out


@pytest.mark.slow
def test_summary_fresh_despite_sparse_logging(tmp_path):
    """A run whose iteration count log_interval never divides must still
    write sweep_summary.json, ranked on the FINAL iteration's rewards."""
    cfg = _cfg(
        tmp_path,
        checkpoint=True,
        log_interval=10,
        total_timesteps=3 * PPO.n_steps * 4 * 3,  # 3 iterations
    )
    sweep = SweepTrainer(
        EnvParams(num_agents=3), ppo=PPO, config=cfg, num_seeds=2
    )
    record = sweep.train()
    assert "reward_best" in record
    summary = json.loads(
        (Path(sweep.log_dir) / "sweep_summary.json").read_text()
    )
    assert len(summary["final_reward"]) == 2


@pytest.mark.slow
def test_periodic_saves_honor_save_freq(tmp_path):
    """save_freq vec-steps between member checkpoints, like Trainer."""
    cfg = _cfg(
        tmp_path,
        checkpoint=True,
        save_freq=PPO.n_steps,  # every iteration
        total_timesteps=2 * PPO.n_steps * 4 * 3,  # 2 iterations
    )
    sweep = SweepTrainer(
        EnvParams(num_agents=3), ppo=PPO, config=cfg, num_seeds=2
    )
    sweep.train()
    ckpts = sorted(
        p.name for p in (Path(sweep.log_dir) / "seed1").glob("*.msgpack")
    )
    assert len(ckpts) == 2, f"expected a checkpoint per iteration: {ckpts}"


@pytest.mark.slow
def test_member_checkpoints_play_back_and_resume(tmp_path):
    """train() writes per-member checkpoints + ranking summary; a member
    checkpoint loads through LoadedPolicy and resumes a single Trainer."""
    from marl_distributedformation_tpu.compat import LoadedPolicy

    params = EnvParams(num_agents=3)
    cfg = _cfg(
        tmp_path,
        checkpoint=True,
        total_timesteps=2 * PPO.n_steps * 4 * 3,  # 2 iterations
    )
    sweep = SweepTrainer(params, ppo=PPO, config=cfg, num_seeds=2)
    record = sweep.train()
    assert "reward_best" in record and "best_seed" in record

    summary = json.loads(
        (Path(sweep.log_dir) / "sweep_summary.json").read_text()
    )
    assert summary["best_dir"] in ("seed0", "seed1")
    assert len(summary["final_reward"]) == 2

    member_dir = Path(sweep.log_dir) / "seed0"
    ckpts = list(member_dir.glob("rl_model_*_steps.msgpack"))
    assert ckpts, f"no member checkpoint in {member_dir}"

    policy = LoadedPolicy.from_checkpoint(ckpts[0], act_dim=2)
    obs = np.zeros((6, params.obs_dim), np.float32)
    actions, _ = policy.predict(obs)
    assert actions.shape == (6, 2)

    resumed = Trainer(
        params,
        ppo=PPO,
        config=_cfg(
            tmp_path, log_dir=str(member_dir), resume=True, checkpoint=False
        ),
    )
    assert resumed.num_timesteps == sweep.num_timesteps
    _leaves_allclose(
        resumed.train_state.params,
        jax.tree_util.tree_map(lambda x: x[0], sweep.train_state.params),
    )


@pytest.mark.slow
def test_sweep_composes_with_ctde_and_gnn(tmp_path):
    """Population training is policy-agnostic: the per-formation CTDE
    critic and the knn-graph GNN both train under the seed vmap."""
    from marl_distributedformation_tpu.models import (
        CTDEActorCritic,
        GNNActorCritic,
    )

    ctde = SweepTrainer(
        EnvParams(num_agents=3),
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=2,
        model=CTDEActorCritic(act_dim=2),
    )
    m = ctde.run_iteration()
    assert np.isfinite(np.asarray(m["loss"])).all()

    kp = EnvParams(num_agents=6, obs_mode="knn", knn_k=2)
    gnn = SweepTrainer(
        kp,
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=2,
        model=GNNActorCritic(k=2, act_dim=2, goal_in_obs=kp.goal_in_obs),
    )
    m = gnn.run_iteration()
    assert np.isfinite(np.asarray(m["loss"])).all()


def test_sweep_burst_retired_and_hetero_rejects_dispatch_fusion(tmp_path):
    """iters_per_dispatch (the reduced-metrics burst) is RETIRED for
    sweeps — fused_chunk is the population fusion spelling
    (tests/test_fused_sweep.py pins its bitwise parity); the single-run
    curriculum trainer still rejects both knobs (host-driven stages)."""
    params = EnvParams(num_agents=3)
    with pytest.raises(SystemExit, match="fused_chunk"):
        SweepTrainer(
            params, ppo=PPO, config=_cfg(tmp_path, iters_per_dispatch=2),
            num_seeds=2,
        )

    from marl_distributedformation_tpu.train import HeteroTrainer

    with pytest.raises(SystemExit, match="iters_per_dispatch"):
        HeteroTrainer(
            env_params=params,
            ppo=PPO,
            config=_cfg(tmp_path, iters_per_dispatch=2),
        )


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
@pytest.mark.parametrize("lr_sweep", [False, True])
def test_sweep_resume_bit_exact(tmp_path, lr_sweep):
    """An interrupted sweep resumed from its sweep_state checkpoint ends
    bit-identical to an uninterrupted run — params, optimizer state
    (incl. per-member injected rates), member keys, env state, and
    progress (VERDICT r3 #3)."""
    params = EnvParams(num_agents=3)
    lrs = [1e-3, 3e-3] if lr_sweep else None
    per_iter = PPO.n_steps * 4 * 3  # n_steps * M * N agent-transitions
    kw = dict(checkpoint=True, save_freq=10**9)

    full = SweepTrainer(
        params, ppo=PPO, num_seeds=2, learning_rates=lrs,
        config=_cfg(tmp_path, name="full", log_dir=str(tmp_path / "full"),
                    total_timesteps=2 * per_iter, **kw),
    )
    full.train()

    half = SweepTrainer(
        params, ppo=PPO, num_seeds=2, learning_rates=lrs,
        config=_cfg(tmp_path, name="part", log_dir=str(tmp_path / "part"),
                    total_timesteps=per_iter, **kw),
    )
    half.train()  # final save() writes sweep_state_{per_iter}_steps
    assert (tmp_path / "part" /
            f"sweep_state_{per_iter}_steps.msgpack").exists()

    resumed = SweepTrainer(
        params, ppo=PPO, num_seeds=2, learning_rates=lrs,
        config=_cfg(tmp_path, name="part", log_dir=str(tmp_path / "part"),
                    total_timesteps=2 * per_iter, resume=True, **kw),
    )
    assert resumed.num_timesteps == per_iter
    resumed.train()

    assert resumed.num_timesteps == full.num_timesteps
    _leaves_equal(resumed.train_state.params, full.train_state.params)
    _leaves_equal(resumed.train_state.opt_state, full.train_state.opt_state)
    _leaves_equal(resumed.key, full.key)
    _leaves_equal(resumed.env_state, full.env_state)
    _leaves_equal(resumed.obs, full.obs)
    # The resumed run's final ranking agrees with the uninterrupted one.
    s_full = json.loads(
        (tmp_path / "full" / "sweep_summary.json").read_text()
    )
    s_res = json.loads(
        (tmp_path / "part" / "sweep_summary.json").read_text()
    )
    assert s_res["best_seed"] == s_full["best_seed"]
    np.testing.assert_array_equal(
        s_res["final_reward"], s_full["final_reward"]
    )


@pytest.mark.slow
def test_sweep_resume_rejects_mismatches(tmp_path):
    """Identity mismatches (population size, lr-sweep mode) must fail
    loudly, not silently re-seed members."""
    params = EnvParams(num_agents=3)
    per_iter = PPO.n_steps * 4 * 3
    cfg = _cfg(
        tmp_path, name="pop", log_dir=str(tmp_path / "pop"),
        checkpoint=True, save_freq=10**9, total_timesteps=per_iter,
    )
    SweepTrainer(params, ppo=PPO, num_seeds=2, config=cfg).train()

    resume_cfg = _cfg(
        tmp_path, name="pop", log_dir=str(tmp_path / "pop"),
        checkpoint=True, save_freq=10**9, total_timesteps=2 * per_iter,
        resume=True,
    )
    with pytest.raises(SystemExit, match="num_seeds"):
        SweepTrainer(params, ppo=PPO, num_seeds=4, config=resume_cfg)
    with pytest.raises(SystemExit, match="learning_rates"):
        SweepTrainer(
            params, ppo=PPO, num_seeds=2, config=resume_cfg,
            learning_rates=[1e-3, 3e-3],
        )

    # Member checkpoints without a population file (pre-feature run):
    # fresh start with a loud note, not a crash.
    import os

    os.remove(
        tmp_path / "pop" / f"sweep_state_{per_iter}_steps.msgpack"
    )
    fresh = SweepTrainer(params, ppo=PPO, num_seeds=2, config=resume_cfg)
    assert fresh.num_timesteps == 0


@pytest.mark.slow
def test_visualize_policy_auto_selects_best_member(
    tmp_path, monkeypatch, capsys
):
    """`visualize_policy.py name=pop` on a sweep run descends into
    sweep_summary.json's best member."""
    import visualize_policy

    cfg = _cfg(
        tmp_path,
        name="popviz",
        log_dir=str(tmp_path / "logs" / "popviz"),
        checkpoint=True,
        total_timesteps=PPO.n_steps * 4 * 3,  # 1 iteration
    )
    sweep = SweepTrainer(
        EnvParams(num_agents=3), ppo=PPO, config=cfg, num_seeds=2
    )
    sweep.train()
    monkeypatch.setattr(
        "marl_distributedformation_tpu.utils.repo_root", lambda: tmp_path
    )
    args = ["name=popviz", "platform=cpu", "headless=true", "steps=2",
            "num_agents_per_formation=3"]
    visualize_policy.main(args)
    out = capsys.readouterr().out
    best = json.loads(
        (Path(sweep.log_dir) / "sweep_summary.json").read_text()
    )["best_dir"]
    assert f"playing best member {best}" in out  # THE ranked member
    assert f"/{best}/rl_model_" in out  # and its checkpoint is loaded

    # Summary exists but its best_dir checkpoint was deleted by hand —
    # fall through to the members scan, not "no checkpoint" (ADVICE r3).
    for p in (Path(sweep.log_dir) / best).glob("rl_model_*_steps*"):
        p.unlink()
    visualize_policy.main(args)
    out = capsys.readouterr().out
    assert "best member missing" in out
    assert "furthest-trained member seed" in out

    # Interrupted sweep: members exist, summary doesn't — fall back to
    # the furthest-trained member instead of claiming nothing exists.
    (Path(sweep.log_dir) / "sweep_summary.json").unlink()
    visualize_policy.main(args)
    assert "furthest-trained member seed" in capsys.readouterr().out


def test_cli_dispatch(tmp_path, monkeypatch):
    import train as train_cli
    from marl_distributedformation_tpu.utils import load_config

    cfg = load_config(
        ["name=sweeptest", "num_seeds=2", "num_formation=4",
         "num_agents_per_formation=3", "platform=cpu"]
    )
    trainer = train_cli.build_trainer(cfg)
    assert isinstance(trainer, SweepTrainer)

    # num_seeds now COMPOSES with curriculum (round 5): the candidate
    # population trainer — its own dispatch/rejection matrix is pinned
    # in tests/test_hetero_sweep.py::test_cli_dispatch.
    from marl_distributedformation_tpu.train import HeteroSweepTrainer

    cfg2 = load_config(
        ["name=x", "num_seeds=2", "platform=cpu", "num_formation=4",
         "num_agents_per_formation=3",
         "curriculum=[{rollouts: 2, agent_counts: [3]}]"]
    )
    assert isinstance(train_cli.build_trainer(cfg2), HeteroSweepTrainer)

    # resume=true now composes with sweeps (population resume): with no
    # prior sweep_state it just builds a fresh population.
    monkeypatch.setattr(train_cli, "repo_root", lambda: tmp_path)
    cfg3 = load_config(
        ["name=x", "num_seeds=2", "resume=true", "platform=cpu",
         "num_formation=4", "num_agents_per_formation=3"]
    )
    trainer3 = train_cli.build_trainer(cfg3)
    assert isinstance(trainer3, SweepTrainer)
    assert trainer3.num_timesteps == 0
