"""Elastic capacity contract (tier-1, multi-device CPU): the live
control loop (serving/elastic) re-splits a serving fleet under a
mixed-size storm without dropping requests, without breaking step
monotonicity, and without a single compile riding the request path.

The acceptance pins from the elastic ISSUE live here:

- a re-split committed under live mixed-size traffic loses ZERO
  accepted requests and serves globally monotonic ``model_step``s
  across the membership swap;
- retired replicas are drained THEN stopped (de-routed at the barrier,
  emptied off-path) — the apply report and the schedulers agree;
- a ledger census diff proves every compile after the fleet's warmup
  is attributed to a prewarm round, never to serving traffic, and the
  budget-1 per-rung receipts hold on the final replica set;
- the hysteresis gate skips a plan equivalent to the one serving, a
  thin window decides nothing, a headroom refusal and an injected
  prewarm fault both abort the round with the old split untouched.
"""

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.chaos import (  # noqa: E402
    FaultSchedule,
    FaultSpec,
    get_fault_plane,
)
from marl_distributedformation_tpu.compat.policy import (  # noqa: E402
    LoadedPolicy,
)
from marl_distributedformation_tpu.models import MLPActorCritic  # noqa: E402
from marl_distributedformation_tpu.obs.ledger import get_ledger  # noqa: E402
from marl_distributedformation_tpu.serving import (  # noqa: E402
    CapacityController,
    TraceRecorder,
)
from marl_distributedformation_tpu.serving.fleet import (  # noqa: E402
    FleetReloadCoordinator,
    FleetRouter,
    warmup_fleet,
)

OBS_DIM = 6


def _make_policy(seed=0):
    model = MLPActorCritic(act_dim=2, hidden=(8, 8))
    variables = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, OBS_DIM))
    )
    return LoadedPolicy(dict(variables), model_kwargs={"hidden": (8, 8)})


def _obs(n, seed=0):
    return (
        np.random.default_rng(seed)
        .standard_normal((n, OBS_DIM))
        .astype(np.float32)
    )


def _elastic_fleet(tmp_path, min_requests=16):
    """A 2-replica fleet on 2 devices with the recorder wired, warm,
    plus its coordinator and controller — the storm fixture."""
    recorder = TraceRecorder()
    router = FleetRouter(
        _make_policy(),
        devices=jax.local_devices()[:2],
        buckets=(1, 8),
        window_ms=0.0,
        trace_recorder=recorder,
    )
    router.start()
    warmup_fleet(router, (OBS_DIM,))
    coordinator = FleetReloadCoordinator(str(tmp_path), router)
    controller = CapacityController(
        router,
        coordinator,
        row_shape=(OBS_DIM,),
        p95_target_ms=50.0,
        min_requests=min_requests,
        drain_timeout_s=5.0,
    )
    recorder.clear()  # warmup traffic is not a capacity signal
    return recorder, router, controller


def _drive(router, sizes, outcomes, steps, seed=0):
    """Submit one request per size; every accepted future must resolve
    (the no-lost-request pin) and successes record (t_done, step)."""
    futures = []
    for i, n in enumerate(sizes):
        futures.append(router.submit(_obs(n, seed=seed + i), timeout_s=5.0))
    for f in futures:
        try:
            result = f.result(timeout=15.0)
        except FutureTimeout:
            outcomes.append("hung")
            continue
        except Exception as e:  # noqa: BLE001 — typed failure = resolved
            outcomes.append(type(e).__name__)
            continue
        outcomes.append("ok")
        steps.append((time.perf_counter(), int(result.model_step)))


def test_resplit_under_mixed_storm(tmp_path):
    recorder, router, controller = _elastic_fleet(tmp_path)
    ledger = get_ledger()
    outcomes, steps = [], []
    try:
        # Big-rung traffic the boot ladder (1, 8) never planned for:
        # fills the recorder past the decision floor.
        _drive(router, [32, 64, 48, 32, 64, 16] * 3, outcomes, steps)
        boot_indices = {r.index for r in router.replicas}

        # Re-split WHILE the storm keeps arriving: a pump thread keeps
        # requests in flight across prewarm, the barrier commit, and
        # the drains.
        stop = threading.Event()

        def _pump():
            batch = 0
            while not stop.is_set():
                _drive(
                    router, [32, 8, 64, 1], outcomes, steps,
                    seed=100 + batch,
                )
                batch += 1

        pump = threading.Thread(target=_pump, daemon=True)
        pump.start()
        try:
            report = controller.step()
        finally:
            stop.set()
            pump.join(timeout=30.0)
        assert report is not None and report["committed"], report

        # Zero lost accepted requests across the swap.
        assert "hung" not in outcomes, outcomes
        assert outcomes and all(o == "ok" for o in outcomes), outcomes

        # Globally monotonic served steps through the commit.
        ordered = [s for _, s in sorted(steps, key=lambda x: x[0])]
        assert all(
            b >= a for a, b in zip(ordered, ordered[1:])
        ), ordered

        # Drained THEN retired: the report counted every boot replica
        # drained clean, and their schedulers are stopped and empty.
        assert report["retired_total"] == len(boot_indices)
        assert report["drained_clean"] == report["retired_total"], report
        live = {r.index for r in router.replicas}
        assert live.isdisjoint(boot_indices), (live, boot_indices)

        # The new ladder actually answers the storm: some live replica
        # owns a rung (or sharded slice) >= the big request sizes.
        top_rung = max(
            max(r.engine.buckets) for r in router.replicas
        )
        assert top_rung >= 32, [
            tuple(r.engine.buckets) for r in router.replicas
        ]

        # Census diff: prewarm accounted for every new ledger entry,
        # and serving the storm after the commit compiled NOTHING.
        assert report["prewarm_compiles"] >= 1, report
        assert len(ledger.entries()) == report["prewarm_programs_after"]
        post_outcomes, post_steps = [], []
        _drive(
            router, [64, 32, 8, 1, 48], post_outcomes, post_steps,
            seed=999,
        )
        assert all(o == "ok" for o in post_outcomes), post_outcomes
        assert len(ledger.entries()) == report["prewarm_programs_after"]
        for counts in router.compile_counts().values():
            assert all(c <= 1 for c in counts.values()), (
                router.compile_counts()
            )

        # Hysteresis: an identical window replayed against the split
        # it just earned is not a decision. (The first commit's plan
        # included the pump's interleaved small requests, so align
        # ``_current_plan`` with the pure mix first — that round may
        # legitimately commit — then replay the SAME mix and require
        # the skip.)
        recorder.clear()
        _drive(router, [32, 64, 48, 32, 64, 16] * 3, [], [])
        controller.step()
        recorder.clear()
        more = []
        _drive(router, [32, 64, 48, 32, 64, 16] * 3, more, [])
        assert all(o == "ok" for o in more), more
        skipped_before = controller.snapshot()["elastic_resplits_skipped"]
        assert controller.step() is None
        assert (
            controller.snapshot()["elastic_resplits_skipped"]
            == skipped_before + 1
        )
    finally:
        router.stop()


def test_thin_window_decides_nothing(tmp_path):
    recorder, router, controller = _elastic_fleet(
        tmp_path, min_requests=16
    )
    try:
        outcomes, steps = [], []
        _drive(router, [4, 8, 2], outcomes, steps)
        assert all(o == "ok" for o in outcomes)
        assert len(recorder) < controller.min_requests
        assert controller.step() is None
        assert (
            controller.snapshot()["elastic_resplits_committed"] == 0
        )
    finally:
        router.stop()


def test_headroom_refusal_keeps_old_split(tmp_path):
    recorder, router, controller = _elastic_fleet(tmp_path)
    controller.headroom_bytes = 1.0  # nothing fits next to the fleet
    try:
        _drive(router, [32, 64] * 10, [], [])
        decision = controller.decide()
        assert decision is not None
        report = controller.apply(decision)
        assert report["skipped"] == "headroom"
        assert not report["committed"]
        # The old split still serves.
        outcomes = []
        _drive(router, [8, 1], outcomes, [])
        assert all(o == "ok" for o in outcomes)
    finally:
        router.stop()


def test_prewarm_fault_aborts_round_old_split_serves(tmp_path):
    recorder, router, controller = _elastic_fleet(tmp_path)
    plane = get_fault_plane()
    plane.reset()
    try:
        _drive(router, [32, 64] * 10, [], [])
        plane.arm(
            FaultSchedule([FaultSpec("elastic.prewarm", "raise", 1)])
        )
        plane.enabled = True
        report = controller.step()
        assert report is not None and not report["committed"], report
        assert "prewarm aborted" in report.get("error", ""), report
        assert (
            controller.snapshot()["elastic_resplits_aborted"] == 1.0
        )
        # Old split intact and serving; no half-built replica routed.
        outcomes = []
        _drive(router, [8, 1, 32], outcomes, [])
        assert all(o == "ok" for o in outcomes), outcomes
        assert all(
            tuple(r.engine.buckets) == (1, 8) for r in router.replicas
        )
    finally:
        plane.enabled = False
        plane.reset()
        router.stop()
