"""Tests for policy networks and the action distribution."""

import chex
import jax
import jax.numpy as jnp
import numpy as np

from marl_distributedformation_tpu.models import MLPActorCritic, distributions


def test_mlp_shapes_and_param_structure():
    model = MLPActorCritic(act_dim=2)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    mean, log_std, value = model.apply(params, jnp.zeros((7, 8)))
    chex.assert_shape(mean, (7, 2))
    chex.assert_shape(log_std, (2,))
    chex.assert_shape(value, (7,))
    # Separate pi/vf towers, 2x64, as SB3 'MlpPolicy' builds them.
    names = set(params["params"].keys())
    assert names == {"pi_0", "pi_1", "pi_head", "vf_0", "vf_1", "vf_head", "log_std"}
    assert params["params"]["pi_0"]["kernel"].shape == (8, 64)
    assert params["params"]["vf_head"]["kernel"].shape == (64, 1)


def test_mlp_leading_batch_axes():
    model = MLPActorCritic(act_dim=2)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    mean, _, value = model.apply(params, jnp.zeros((4, 5, 8)))
    chex.assert_shape(mean, (4, 5, 2))
    chex.assert_shape(value, (4, 5))


def test_log_std_init_knob():
    """Q5: log_std_init is a real knob here; parity default is 0.0."""
    for init in (0.0, -2.0):
        model = MLPActorCritic(act_dim=2, log_std_init=init)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
        np.testing.assert_allclose(
            np.asarray(params["params"]["log_std"]), init
        )


def test_orthogonal_init_gains():
    model = MLPActorCritic(act_dim=2)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    # Hidden kernels: orthogonal with gain sqrt(2) -> columns have norm
    # sqrt(2) (64x64 square case gives exact orthogonality * gain).
    k = np.asarray(params["params"]["pi_1"]["kernel"])
    np.testing.assert_allclose(
        k.T @ k, 2.0 * np.eye(64), atol=1e-4
    )
    # Action head gain 0.01: tiny initial action means.
    head = np.asarray(params["params"]["pi_head"]["kernel"])
    assert np.abs(head).max() < 0.01


def test_gaussian_log_prob_matches_scipy_formula():
    key = jax.random.PRNGKey(1)
    mean = jnp.array([[0.5, -1.0]])
    log_std = jnp.array([0.3, -0.7])
    x = jnp.array([[0.1, 0.2]])
    lp = distributions.log_prob(x, mean, log_std)
    std = np.exp(np.asarray(log_std))
    expected = -0.5 * (
        ((np.asarray(x) - np.asarray(mean)) / std) ** 2
        + np.log(2 * np.pi)
    ) - np.log(std)
    np.testing.assert_allclose(float(lp[0]), expected.sum(), rtol=1e-5)

    # Sampling is reparameterized and respects the std.
    samples = distributions.sample(
        key, jnp.zeros((20000, 2)), jnp.log(jnp.array([0.5, 2.0]))
    )
    np.testing.assert_allclose(
        np.asarray(samples).std(axis=0), [0.5, 2.0], rtol=0.05
    )


def test_gaussian_entropy():
    log_std = jnp.array([0.0, 0.0])
    expected = 2 * 0.5 * (1 + np.log(2 * np.pi))
    np.testing.assert_allclose(
        float(distributions.entropy(log_std)), expected, rtol=1e-6
    )
