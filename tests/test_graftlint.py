"""graftlint tier-1 contract: every rule fires on a known-bad fixture,
stays quiet on the known-good twin, and the package itself is clean.

The package scan is the point of the subsystem (ISSUE: the linter
*proves* the loop stays compiled and device-resident, permanently, in
CI); the fixture pairs pin each rule's detection so a refactor of the
engine cannot silently lobotomize a rule while the package scan still
reports zero.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "marl_distributedformation_tpu"

from marl_distributedformation_tpu.analysis import (  # noqa: E402
    GraftlintConfig,
    lint_paths,
    lint_source,
)
from marl_distributedformation_tpu.analysis.config import (  # noqa: E402
    config_from_dict,
)
from marl_distributedformation_tpu.analysis.rules import rule_names  # noqa: E402


def lint(src):
    """Lint a fixture. A plain string is one in-memory module; a dict
    ``{filename: source}`` is a multi-file fixture written to a real
    temp directory (cross-module rules resolve imports on disk) with
    ``main.py`` as the linted module."""
    if isinstance(src, dict):
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            d = Path(td)
            for name, content in src.items():
                (d / name).write_text(textwrap.dedent(content))
            return lint_source(
                textwrap.dedent(src["main.py"]), str(d / "main.py")
            )
    return lint_source(textwrap.dedent(src), "fixture.py")


def fired(src):
    return {v.rule for v in lint(src)}


# ---------------------------------------------------------------------------
# Rule fixtures: (rule, known-bad, known-good)
# ---------------------------------------------------------------------------

FIXTURES = [
    (
        "numpy-in-jit",
        """
        import jax, numpy as np

        @jax.jit
        def f(x):
            return np.sum(x)  # host numpy on a traced arg
        """,
        """
        import jax, jax.numpy as jnp, numpy as np

        @jax.jit
        def f(x):
            table = np.arange(4)  # static constant: allowed
            return jnp.sum(x) + table[0]
        """,
    ),
    (
        "traced-python-control-flow",
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            s = jnp.sum(x)
            if s > 0:
                return x
            return -x
        """,
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x, params, with_obs=True):
            if params.strict_parity:   # static config: allowed
                x = x + 1
            if x.shape[0] > 2:         # static shape: allowed
                x = x * 2
            if with_obs:               # literal-default flag: allowed
                x = x - 1
            if x is None:              # structural: allowed
                return x
            return jnp.where(jnp.sum(x) > 0, x, -x)
        """,
    ),
    (
        "traced-python-control-flow",
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            while jnp.abs(x).max() > 1.0:
                x = x * 0.5
            return x
        """,
        """
        import jax
        from jax import lax

        @jax.jit
        def f(x):
            return lax.while_loop(lambda v: False, lambda v: v, x)
        """,
    ),
    (
        "prng-key-reuse",
        """
        import jax

        def sample(key):
            a = jax.random.uniform(key, (3,))
            b = jax.random.normal(key, (3,))  # same key: correlated draws
            return a + b
        """,
        """
        import jax

        def sample(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.uniform(k1, (3,))
            b = jax.random.normal(k2, (3,))
            return a + b
        """,
    ),
    (
        "prng-key-reuse",
        """
        import jax
        from jax import lax

        def rollout(key, carry, xs):
            # scan body as a lambda — the idiomatic home of per-step keys
            return lax.scan(
                lambda c, x: (c, jax.random.normal(key) + jax.random.uniform(key)),
                carry, xs,
            )
        """,
        """
        import jax
        from jax import lax

        def rollout(key, carry, xs):
            return lax.scan(
                lambda c, x: (c, jax.random.normal(x)), carry, xs
            )
        """,
    ),
    (
        "prng-key-reuse",
        """
        import jax

        def rollout(key, n):
            outs = []
            for _ in range(n):
                outs.append(jax.random.uniform(key))  # reused every iter
            return outs
        """,
        """
        import jax

        def rollout(key, n):
            outs = []
            for _ in range(n):
                key, k = jax.random.split(key)
                outs.append(jax.random.uniform(k))
            return outs
        """,
    ),
    (
        "host-sync-in-jit",
        """
        import jax

        @jax.jit
        def f(x):
            return float(x.sum())  # concretizes the tracer
        """,
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.float32(x.sum())
        """,
    ),
    (
        "host-sync-in-jit",
        """
        import jax, numpy as np

        @jax.jit
        def f(x):
            y = x * 2
            return np.asarray(y)  # device->host pull
        """,
        """
        import numpy as np

        def host_metrics(metrics):  # not traced: syncs are fine here
            return {k: float(v) for k, v in metrics.items()}
        """,
    ),
    (
        "mutable-capture-in-jit",
        """
        import jax

        @jax.jit
        def f(x, acc=[]):
            acc.append(1)  # trace-time side effect
            return x
        """,
        """
        import jax

        @jax.jit
        def f(x, scale=1.0):
            return x * scale
        """,
    ),
    (
        "mutable-capture-in-jit",
        """
        import jax

        _count = 0

        @jax.jit
        def f(x):
            global _count
            _count += 1  # advances once per COMPILE, not per step
            return x
        """,
        """
        import jax

        _TABLE = (1, 2, 3)

        @jax.jit
        def f(x):
            return x * _TABLE[0]  # reading module constants is fine
        """,
    ),
    (
        "deprecated-api",
        """
        import jax

        def make(mesh, spec, f):
            return jax.shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec)
        """,
        """
        from marl_distributedformation_tpu.jax_compat import shard_map

        def make(mesh, spec, f):
            return shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec)
        """,
    ),
    (
        "deprecated-api",
        """
        from jax.experimental.shard_map import shard_map
        """,
        """
        from jax.experimental import mesh_utils
        """,
    ),
    (
        "missing-donate",
        """
        import jax

        def make(train_iteration):
            return jax.jit(train_iteration)  # prev state stays live
        """,
        """
        import jax

        def make(train_iteration):
            donating = jax.jit(train_iteration, donate_argnums=(0, 1))
            iteration_no_donate = jax.jit(train_iteration)  # documented twin
            return donating, iteration_no_donate
        """,
    ),
    (
        "print-in-jit",
        """
        import jax

        @jax.jit
        def f(x):
            print("stepping", x)  # trace-time only
            return x
        """,
        """
        import jax

        @jax.jit
        def f(x):
            jax.debug.print("stepping {}", x)
            return x
        """,
    ),
    (
        "print-in-jit",
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            y = jnp.sum(x)
            msg = f"sum was {y}"  # bakes in the tracer repr
            return x, msg
        """,
        """
        import jax

        @jax.jit
        def f(x, k=4):
            n = x.shape[0]
            assert k < n, f"need k < N (k={k}, N={n})"  # static + failure path
            return x
        """,
    ),
    (
        "scan-carry-weak-type",
        """
        import jax
        from jax import lax

        def rollout(body, x, xs):
            # 0.0 is a weak-typed Python scalar: the body's arithmetic
            # promotes it and the carry comes back a different aval.
            return lax.scan(body, (x, 0.0), xs)
        """,
        """
        import jax, jax.numpy as jnp
        from jax import lax

        def rollout(body, x, xs):
            carry = (x, jnp.asarray(0.0, jnp.float32))
            out = lax.scan(body, carry, xs)
            # literals inside constructors are strong-typed: fine
            return lax.scan(body, (x, jnp.zeros((4,))), xs), out
        """,
    ),
    (
        "scan-carry-weak-type",
        """
        import jax

        def count(body, xs):
            # keyword init + unary sign both reach the literal
            return jax.lax.scan(body, init=-1, xs=xs)
        """,
        """
        import jax, jax.numpy as jnp

        def count(body, xs, n0):
            # int dict KEYS are pytree structure, not carry leaves
            out = jax.lax.scan(body, init={0: n0, 1: n0}, xs=xs)
            return jax.lax.scan(body, init=n0, xs=xs), out
        """,
    ),
    (
        "vmap-in-axes-arity",
        """
        import jax

        def f(x, y):
            return x + y

        def run(a, b):
            # signature drifted: f takes 2 args, the axes spec says 3
            return jax.vmap(f, in_axes=(0, None, 0))(a, b, b)
        """,
        """
        import jax, functools

        def f(x, y, scale=1.0):
            return (x + y) * scale

        def g(x, y):
            return x + y

        g = functools.partial(g, y=1)  # rebound: arity untrustworthy

        def run(a, b):
            two = jax.vmap(f, in_axes=(0, None))(a, b)       # default ok
            three = jax.vmap(f, in_axes=(0, None, None))(a, b, 2.0)
            # wrapped targets change the effective arity: out of scope
            part = jax.vmap(
                functools.partial(f, scale=2.0), in_axes=(0, None)
            )(a, b)
            one = jax.vmap(g, in_axes=(0,))(a)  # rebound name: skipped
            return two, three, part, one
        """,
    ),
    (
        "implicit-f64-promotion",
        """
        import jax, numpy as np

        @jax.jit
        def f(x):
            scale = np.float64(0.5)          # f64 scalar at trace time
            y = x * np.array([0.5, 1.5])     # host f64 mixed with traced
            return (y * scale).astype(np.float64)
        """,
        """
        import jax, jax.numpy as jnp, numpy as np

        @jax.jit
        def f(x):
            y = x * 0.5                      # weak literal: adopts x's dtype
            table = np.array([0.5, 1.5], dtype=np.float32)  # pinned
            z = y + jnp.asarray(table)
            counts = x + np.arange(4)        # int arange: not an f64 source
            return z.astype(jnp.float32), counts
        """,
    ),
    (
        "implicit-f64-promotion",
        """
        import jax, jax.numpy as jnp, numpy as np

        @jax.jit
        def g(x):
            grid = jnp.zeros((4,), dtype=float)  # builtin float == f64
            return x + grid, x * np.linspace(0.0, 1.0, 4)
        """,
        """
        import numpy as np

        def host_report(arr):
            # not traced: host-side f64 statistics are fine
            return np.float64(arr).mean() + np.linspace(0.0, 1.0, 4)
        """,
    ),
    (
        "vmap-in-axes-arity",
        """
        import jax

        def run(a, b, g):
            # g is imported/opaque — but the immediate call disagrees
            # with the axes tuple, which is checkable syntactically
            return jax.vmap(g, in_axes=(0, 0))(a, b, b)
        """,
        """
        import jax

        def run(a, b, g):
            mapped = jax.vmap(g, in_axes=(0, None))(a, b)
            star = jax.vmap(g, in_axes=(0, None))(*[a, b, b])  # skipped
            scalar = jax.vmap(g, in_axes=0)(a, b, b)  # int spec: skipped
            return mapped, star, scalar
        """,
    ),
    (
        "callback-in-hot-loop",
        """
        import jax, jax.numpy as jnp
        from jax import lax

        def train(xs):
            def body(carry, x):
                jax.debug.print("reward {r}", r=x)  # host RTT per step
                return carry + x, x
            return lax.scan(body, jnp.zeros(()), xs)
        """,
        """
        import jax, jax.numpy as jnp
        from jax import lax

        @jax.jit
        def debug_step(x):
            # one transfer per dispatch, not inside a compiled loop: fine
            jax.debug.print("x = {x}", x=x)
            return x * 2

        def train(xs):
            def body(carry, x):
                return carry + x, x  # telemetry stacked in the scan output
            carry, stacked = lax.scan(body, jnp.zeros(()), xs)
            jax.debug.print("chunk done: {c}", c=carry)  # once per chunk
            return carry, stacked
        """,
    ),
    (
        "callback-in-hot-loop",
        """
        import jax
        from jax import lax

        def emit(metrics):
            jax.experimental.io_callback(print, None, metrics)

        def train(steps, state):
            def body(i, state):
                emit(state)  # reaches io_callback: host RTT per step
                return state
            return lax.fori_loop(0, steps, body, state)
        """,
        """
        import jax
        from jax import lax

        def emit(metrics):
            jax.experimental.io_callback(print, None, metrics)

        def train(steps, state):
            def body(i, state):
                return state
            state = lax.fori_loop(0, steps, body, state)
            emit(state)  # outside the loop: once per chunk, fine
            return state
        """,
    ),
    (
        "scan-carry-sharding-drift",
        """
        import functools
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        @functools.partial(jax.jit, donate_argnums=(0,))
        def train(state, xs):
            def body(carry, x):
                h = carry + x
                h = lax.with_sharding_constraint(h, P())  # drifted
                return h, h
            init = lax.with_sharding_constraint(state, P("dp"))
            return lax.scan(body, init, xs)

        def shadowed(state, xs):
            # the body REUSES the init's name — its rebind is a
            # different scope and must not mask the init's spec
            state = lax.with_sharding_constraint(state, P("dp"))
            def walk(carry, x):
                state = lax.with_sharding_constraint(carry + x, P())
                return state, state
            return lax.scan(walk, state, xs)
        """,
        """
        import functools
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def other(x):
            # sibling function binding the same name at another spec:
            # never poisons train's init lookup
            init = lax.with_sharding_constraint(x, P(None))
            return init

        @functools.partial(jax.jit, donate_argnums=(0,))
        def train(state, xs):
            def body(carry, x):
                h = lax.with_sharding_constraint(carry + x, P("dp"))
                return h, h
            init = lax.with_sharding_constraint(state, P("dp"))
            return lax.scan(body, init, xs)

        def train2(state, xs):
            def walk(carry, x):
                h = lax.with_sharding_constraint(carry + x, P("dp"))
                return h, h
            # init unannotated: propagation decides both consistently
            return lax.scan(walk, state, xs)
        """,
    ),
    (
        "scan-carry-sharding-drift",
        """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def step(nn_params, acc, xs):
            p0 = lax.with_sharding_constraint(nn_params, P("dp"))
            def body(carry, x):
                p, a = carry
                p = lax.with_sharding_constraint(p, P(None))  # drifted
                return (p, a + x), a
            return lax.scan(body, (p0, acc), xs)
        """,
        """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def step(nn_params, acc, xs):
            p0 = lax.with_sharding_constraint(nn_params, P("dp"))
            def body(carry, x):
                p, a = carry
                p = lax.with_sharding_constraint(p, P("dp"))
                return (p, a + x), a
            return lax.scan(body, (p0, acc), xs)
        """,
    ),
    (
        # Cross-module reachability: the callback hides one `from x
        # import f` away — invisible to rule 12's same-module hop.
        "cross-module-callback",
        {
            "main.py": """
            import jax
            from jax import lax
            from telemetry import emit

            def train(xs):
                def body(carry, x):
                    emit(x)  # io_callback lives in telemetry.py
                    return carry + x, x
                return lax.scan(body, 0.0, xs)
            """,
            "telemetry.py": """
            import jax

            def emit(metrics):
                jax.experimental.io_callback(print, None, metrics)
            """,
        },
        {
            "main.py": """
            import jax
            from jax import lax
            from telemetry import emit, fold

            def train(xs):
                def body(carry, x):
                    return fold(carry, x), x  # imported but pure: clean
                carry, stacked = lax.scan(body, 0.0, xs)
                emit(stacked)  # outside the loop: once per chunk, fine
                return carry, stacked
            """,
            "telemetry.py": """
            import jax

            def emit(metrics):
                jax.experimental.io_callback(print, None, metrics)

            def fold(carry, x):
                return carry + x
            """,
        },
    ),
    (
        # Same hazard via a module alias (`import pkg_mod as telem;
        # telem.emit(...)`) inside a fori_loop body.
        "cross-module-callback",
        {
            "main.py": """
            import jax
            from jax import lax
            import telem

            def train(steps, state):
                def body(i, state):
                    telem.emit(state)  # reaches jax.debug.callback
                    return state
                return lax.fori_loop(0, steps, body, state)
            """,
            "telem.py": """
            import jax

            def emit(state):
                jax.debug.callback(print, state)
            """,
        },
        {
            "main.py": """
            import jax
            from jax import lax
            import telem

            def emit(state):
                # same-module def SHADOWS the import target name space:
                # plain `emit(...)` here is rule 12's domain, not ours
                return state

            def train(steps, state):
                def body(i, state):
                    emit(state)  # resolves to the local, clean def
                    return telem.scale(state)  # imported but pure
                state = lax.fori_loop(0, steps, body, state)
                telem.emit(state)  # outside the loop: fine
                return state
            """,
            "telem.py": """
            import jax

            def emit(state):
                jax.debug.callback(print, state)

            def scale(state):
                return state * 2
            """,
        },
    ),
    (
        # Host-side tracing recorded INSIDE a jitted function: the span
        # closes at trace time, measuring one compile and zero
        # executions — and host work has leaked into the compiled scope.
        "span-in-traced-scope",
        """
        import jax
        from marl_distributedformation_tpu.obs import get_tracer

        tracer = get_tracer()

        @jax.jit
        def step(x):
            with tracer.span("step"):
                return x * 2
        """,
        """
        import jax
        from marl_distributedformation_tpu.obs import get_tracer

        tracer = get_tracer()

        @jax.jit
        def step(x):
            return x * 2

        def dispatch(x):
            # the dispatch seam: span wraps the jitted CALL, host-side
            with tracer.span("step"):
                return step(x)
        """,
    ),
    (
        # Same hazard one hop away inside a scan body: the helper's
        # event() call would record per trace, not per iteration — and
        # via get_tracer() it is invisible to a receiver-name check.
        "span-in-traced-scope",
        """
        import jax
        from jax import lax
        from marl_distributedformation_tpu.obs import get_tracer

        def note(x):
            get_tracer().event("iteration", value=0)

        def train(xs):
            def body(carry, x):
                note(x)
                return carry + x, x
            return lax.scan(body, 0.0, xs)
        """,
        """
        import jax
        from jax import lax
        from marl_distributedformation_tpu.obs import get_tracer

        def train(xs):
            def body(carry, x):
                return carry + x, x
            with get_tracer().span("train.chunk"):
                carry, stacked = lax.scan(body, 0.0, xs)
            get_tracer().event("chunk_done")
            return carry, stacked
        """,
    ),
    (
        # Params re-placed per request inside the serve loop: a full
        # host->device weight upload every dispatch. The good twin
        # places ONCE before the loop (the swap/commit seam) and
        # dispatches against the device-resident tree.
        "device-put-in-dispatch-loop",
        """
        import jax

        def serve_loop(q, params, device, engine):
            while True:
                req = q.get()
                placed = jax.device_put(params, device)  # per request!
                engine.act(placed, req)
        """,
        """
        import jax

        def serve_loop(q, params, device, engine):
            placed = jax.device_put(params, device)  # once, at build
            while True:
                req = q.get()
                engine.act(placed, req)
        """,
    ),
    (
        # The same hazard one plain-name call hop away: the loop calls
        # a helper that performs the placement. The good twin's helper
        # is only called outside the loop (and an amortized batched
        # device_get drain in the loop stays clean — gets are the
        # runtime guard's business, per the trainer's log-interval
        # drain idiom).
        "device-put-in-dispatch-loop",
        """
        import jax

        def _place(params, device):
            return jax.device_put(params, device)

        def serve_loop(q, params, device, engine):
            while not q.empty():
                req = q.get()
                engine.act(_place(params, device), req)
        """,
        """
        import jax

        def _place(params, device):
            return jax.device_put(params, device)

        def serve_loop(q, params, device, engine, metrics):
            placed = _place(params, device)
            i = 0
            while not q.empty():
                req = q.get()
                engine.act(placed, req)
                i += 1
                if i % 100 == 0:
                    jax.device_get(metrics)  # amortized drain: clean
        """,
    ),
    (
        # Rule 17: the evolutionary-search foot-gun — a lax loop body
        # selects candidates through a module-level helper that Python-
        # branches on a comparison of its (traced) arguments. Rule 2
        # cannot see it (the helper is not itself a traced scope); the
        # one-hop follow reports it at the call site.
        "traced-python-comparison-in-search",
        """
        import jax
        from jax import lax

        def better(best, cand):
            if cand > best:  # concretizes under the while_loop trace
                return cand
            return best

        def search(fitness):
            def body(state):
                i, best = state
                return i + 1, better(best, fitness[i])

            return lax.while_loop(lambda s: s[0] < 8, body, (0, fitness[0]))
        """,
        """
        import jax, jax.numpy as jnp
        from jax import lax

        def better(best, cand):
            return jnp.where(cand > best, cand, best)  # stays in-program

        def search(fitness):
            def body(state):
                i, best = state
                return i + 1, better(best, fitness[i])

            return lax.while_loop(lambda s: s[0] < 8, body, (0, fitness[0]))
        """,
    ),
    (
        # Rule 17, jitted-generation-loop shape: a host `for` loop fused
        # wholesale into a jitted search calls a threshold helper whose
        # `while` compares traced arguments.
        "traced-python-comparison-in-search",
        """
        import jax, jax.numpy as jnp

        def clamp(cur, cand, limit):
            while cand > cur + limit:  # traced comparison, Python loop
                cand = cand * 0.5
            return cand

        @jax.jit
        def evolve(pop, limit):
            best = pop[0]
            for _ in range(4):  # generation loop, jitted wholesale
                best = clamp(best, pop.max(), limit)
            return best
        """,
        """
        import jax, jax.numpy as jnp

        def clamp(cur, cand, keep_best=True):
            if keep_best:  # literal-default flag: static, allowed
                return jnp.maximum(cur, cand)
            return cand

        @jax.jit
        def evolve(pop):
            best = pop[0]
            for _ in range(4):
                best = clamp(best, pop.max())
            return best
        """,
    ),
    (
        # Rule 18: MetricsRegistry recording under trace — the counter
        # bumps once at COMPILE time, then never again, while the code
        # looks instrumented. The good twin records at the dispatch
        # seam around the jitted call.
        "metrics-in-traced-scope",
        """
        import jax
        from marl_distributedformation_tpu.obs.metrics import get_registry

        @jax.jit
        def step(x):
            get_registry().counter("steps_total").inc()
            return x * 2
        """,
        """
        import jax
        from marl_distributedformation_tpu.obs.metrics import get_registry

        @jax.jit
        def step(x):
            return x * 2

        def dispatch(x):
            out = step(x)
            get_registry().counter("steps_total").inc()
            return out
        """,
    ),
    (
        # Same hazard one hop away inside a scan body, through a
        # registry-receiver chain: the helper's observe() would record
        # per trace, not per iteration. The good twin's helper is only
        # called from the host-side drain.
        "metrics-in-traced-scope",
        """
        from jax import lax

        def note(registry, dt):
            registry.histogram("iter_seconds").observe(dt)

        def train(registry, xs):
            def body(carry, x):
                note(registry, x)
                return carry + x, x
            return lax.scan(body, 0.0, xs)
        """,
        """
        from jax import lax

        def note(registry, dt):
            registry.histogram("chunk_seconds").observe(dt)

        def train(registry, xs):
            def body(carry, x):
                return carry + x, x
            carry, stacked = lax.scan(body, 0.0, xs)
            note(registry, 0.1)  # the drain seam: host-side
            registry.gauge("steps_per_sec").set(1.0)
            return carry, stacked
        """,
    ),
    (
        # Rule 19: a chaos injection point under trace — the armed
        # fault fires once at COMPILE time (or unwinds the tracer
        # itself) while the campaign believes it exercises every step.
        # The good twin injects at the dispatch seam around the call.
        "fault-point-in-traced-scope",
        """
        import jax
        from marl_distributedformation_tpu.chaos import fault_point

        @jax.jit
        def step(x):
            fault_point("trainer.step")
            return x * 2
        """,
        """
        import jax
        from marl_distributedformation_tpu.chaos import fault_point

        @jax.jit
        def step(x):
            return x * 2

        def dispatch(x):
            fault_point("trainer.dispatch")
            return step(x)
        """,
    ),
    (
        # Same hazard one hop away inside a scan body, through the
        # plane-receiver chain: the helper's hit() would count per
        # trace, not per iteration. The good twin's helper is only
        # called from the host-side drain, and an unrelated .hit()
        # receiver stays clean.
        "fault-point-in-traced-scope",
        """
        from jax import lax
        from marl_distributedformation_tpu.chaos import get_fault_plane

        def poke():
            get_fault_plane().hit("sweep.member")

        def train(xs):
            def body(carry, x):
                poke()
                return carry + x, x
            return lax.scan(body, 0.0, xs)
        """,
        """
        from jax import lax
        from marl_distributedformation_tpu.chaos import get_fault_plane

        def poke():
            get_fault_plane().hit("sweep.drain")

        def train(xs, target):
            def body(carry, x):
                target.hit(x)  # not plane-like: stays clean
                return carry + x, x
            carry, stacked = lax.scan(body, 0.0, xs)
            poke()  # the drain seam: host-side
            return carry, stacked
        """,
    ),
    (
        # Ledger dispatch recording inside a jitted body measures the
        # trace, not the dispatches. The good twin records at the host
        # seam around the jitted call — the ledgered_jit discipline.
        "ledger-record-in-traced-scope",
        """
        import jax
        from marl_distributedformation_tpu.obs.ledger import get_ledger

        @jax.jit
        def step(x):
            get_ledger().dispatch("trainer_step", 0.001)
            return x * 2
        """,
        """
        import jax
        import time
        from marl_distributedformation_tpu.obs.ledger import get_ledger

        @jax.jit
        def step(x):
            return x * 2

        def dispatch(x):
            t0 = time.perf_counter()
            out = step(x)
            get_ledger().dispatch("trainer_step", time.perf_counter() - t0)
            return out
        """,
    ),
    (
        # Same hazard one hop away inside a scan body, through a
        # ledger-receiver chain; the good twin's helper runs at the
        # drain seam, and an unrelated ``.register()`` receiver
        # (atexit-shaped) stays clean.
        "ledger-record-in-traced-scope",
        """
        from jax import lax
        from marl_distributedformation_tpu.obs import ledger

        def note(ledger_handle):
            ledger_handle.record_watermark(1024.0)

        def train(xs, ledger_handle):
            def body(carry, x):
                note(ledger_handle)
                return carry + x, x
            return lax.scan(body, 0.0, xs)
        """,
        """
        import atexit
        from jax import lax
        from marl_distributedformation_tpu.obs import ledger

        def note():
            ledger.get_ledger().record_watermark(1024.0)

        def train(xs, hooks):
            def body(carry, x):
                hooks.register(x)  # not ledger-like: stays clean
                return carry + x, x
            carry, stacked = lax.scan(body, 0.0, xs)
            note()  # the drain seam: host-side
            return carry, stacked
        """,
    ),
    (
        # Rule 21: a mesh RPC round trip under trace fires once per
        # COMPILE and wedges the tracer on a dead peer. The good twin
        # makes the coordinator call at the dispatch seam around the
        # jitted call.
        "rpc-in-traced-scope",
        """
        import jax
        from marl_distributedformation_tpu.serving.mesh.rpc import rpc_call

        @jax.jit
        def step(x):
            rpc_call("http://127.0.0.1:9", "mesh.heartbeat", {})
            return x * 2
        """,
        """
        import jax
        from marl_distributedformation_tpu.serving.mesh.rpc import rpc_call

        @jax.jit
        def step(x):
            return x * 2

        def dispatch(x):
            out = step(x)
            rpc_call("http://127.0.0.1:9", "mesh.heartbeat", {})
            return out
        """,
    ),
    (
        # Same hazard one hop away inside a scan body, through a
        # mesh-receiver chain and a raw socket-module call; the good
        # twin's helper runs at the host seam, and an unrelated
        # ``registry.register(...)`` receiver stays clean.
        "rpc-in-traced-scope",
        """
        import socket
        from jax import lax

        def phone_home(coordinator):
            coordinator.global_reload("ckpt")
            socket.create_connection(("127.0.0.1", 9))

        def train(xs, coordinator):
            def body(carry, x):
                phone_home(coordinator)
                return carry + x, x
            return lax.scan(body, 0.0, xs)
        """,
        """
        import socket
        from jax import lax

        def phone_home(coordinator):
            coordinator.global_reload("ckpt")
            socket.create_connection(("127.0.0.1", 9))

        def train(xs, coordinator, registry):
            def body(carry, x):
                registry.register(x)  # not mesh-like: stays clean
                return carry + x, x
            carry, stacked = lax.scan(body, 0.0, xs)
            phone_home(coordinator)  # the dispatch seam: host-side
            return carry, stacked
        """,
    ),
    (
        # Rule 22: per-iteration host finiteness polling of a device
        # value forces one sync per dispatch (and sees fused divergence
        # K iterations late). The good twin computes the health word
        # in-program and drains it batched — np over the DRAINED numpy
        # stack is the legitimate spelling.
        "host-nonfinite-probe-in-dispatch-loop",
        """
        import jax
        import jax.numpy as jnp

        def train(step, state, total):
            i = 0
            while i < total:
                state, loss = step(state)
                if jnp.isnan(loss).any():  # device sync per iteration
                    break
                i += 1
            return state
        """,
        """
        import jax
        import numpy as np

        def train(step_chunk, state, chunks):
            stacks = []
            for _ in range(chunks):
                state, stacked = step_chunk(state)  # health word rides
                stacks.append(stacked)              # the chunk metrics
            drained = jax.device_get(stacks)  # ONE batched drain
            flags = np.concatenate([s["health_ok"] for s in drained])
            skipped = int((flags < 0.5).sum())  # np over host data: clean
            return state, skipped
        """,
    ),
    (
        # Same hazard spelled as float()-pull probes — math.isnan over
        # a forced transfer, one hop into a helper — in a for-loop
        # dispatch body. The good twin keeps the float() pulls (the
        # drain's legitimate log path) but probes finiteness only once,
        # AFTER the loop.
        "host-nonfinite-probe-in-dispatch-loop",
        """
        import math

        def diverged(metrics):
            return math.isnan(float(metrics["loss"]))

        def train(step, state, total):
            for _ in range(total):
                state, metrics = step(state)
                if diverged(metrics):  # reaches math.isnan(float(...))
                    break
            return state
        """,
        """
        import math

        def train(step, state, total):
            record = {}
            for _ in range(total):
                state, metrics = step(state)
                record = {k: float(v) for k, v in metrics.items()}
            final_ok = not math.isnan(float(record["loss"]))  # once, post-loop
            return state, final_ok
        """,
    ),
    (
        # Rule 23: three locks acquired pairwise in a ring (a→b, b→c,
        # c→a) — two threads entering from different edges deadlock.
        # The good twin acquires the same locks in one global order.
        "lock-ordering-cycle",
        """
        import threading

        class Pool:
            def __init__(self):
                self.a_lock = threading.Lock()
                self.b_lock = threading.Lock()
                self.c_lock = threading.Lock()

            def ab(self):
                with self.a_lock:
                    with self.b_lock:
                        pass

            def bc(self):
                with self.b_lock:
                    with self.c_lock:
                        pass

            def ca(self):
                with self.c_lock:
                    with self.a_lock:
                        pass
        """,
        """
        import threading

        class Pool:
            def __init__(self):
                self.a_lock = threading.Lock()
                self.b_lock = threading.Lock()
                self.c_lock = threading.Lock()

            def ab(self):
                with self.a_lock:
                    with self.b_lock:
                        pass

            def bc(self):
                with self.b_lock:
                    with self.c_lock:
                        pass

            def ac(self):
                with self.a_lock:
                    with self.c_lock:
                        pass
        """,
    ),
    (
        # Rule 23 again: a two-lock inversion hidden behind a call —
        # flush holds read_lock and calls a helper that takes
        # write_lock, while compact nests them the other way round.
        # The good twin gives compact the same read→write order.
        "lock-ordering-cycle",
        """
        import threading

        class Store:
            def __init__(self):
                self.read_lock = threading.Lock()
                self.write_lock = threading.Lock()

            def flush(self):
                with self.read_lock:
                    self._sync()

            def _sync(self):
                with self.write_lock:
                    pass

            def compact(self):
                with self.write_lock:
                    with self.read_lock:
                        pass
        """,
        """
        import threading

        class Store:
            def __init__(self):
                self.read_lock = threading.Lock()
                self.write_lock = threading.Lock()

            def flush(self):
                with self.read_lock:
                    self._sync()

            def _sync(self):
                with self.write_lock:
                    pass

            def compact(self):
                with self.read_lock:
                    with self.write_lock:
                        pass
        """,
    ),
    (
        # Rule 24: an attribute declared guarded-by a lock, written
        # from a thread-reachable method without holding it. The good
        # twin wraps the write.
        "unguarded-shared-mutation",
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0  # graftlock: guarded-by=_lock

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()

            def _worker(self):
                self.total = self.total + 1
        """,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0  # graftlock: guarded-by=_lock

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()

            def _worker(self):
                with self._lock:
                    self.total = self.total + 1
        """,
    ),
    (
        # Rule 24 again: the unguarded write hides one call deep — the
        # thread entry calls a helper that mutates. The good twin holds
        # the lock at the caller; the held context flows through the
        # call edge, so the helper needs no lock of its own.
        "unguarded-shared-mutation",
        """
        import threading

        class Ring:
            def __init__(self):
                self._lock = threading.Lock()
                self.head = 0  # graftlock: guarded-by=_lock

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self._advance()

            def _advance(self):
                self.head = self.head + 1
        """,
        """
        import threading

        class Ring:
            def __init__(self):
                self._lock = threading.Lock()
                self.head = 0  # graftlock: guarded-by=_lock

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._advance()

            def _advance(self):
                self.head = self.head + 1
        """,
    ),
    (
        # Rule 25: sleeping while the batch gate is held keeps every
        # replica's barrier closed for the duration. The good twin
        # sleeps after releasing it.
        "blocking-call-under-dispatch-lock",
        """
        import threading
        import time

        class Dispatcher:
            def __init__(self):
                self.batch_lock = threading.Lock()
                self.backoff_s = 0.5

            def flush(self):
                with self.batch_lock:
                    time.sleep(self.backoff_s)
        """,
        """
        import threading
        import time

        class Dispatcher:
            def __init__(self):
                self.batch_lock = threading.Lock()
                self.backoff_s = 0.5

            def flush(self):
                with self.batch_lock:
                    pending = self.backoff_s
                time.sleep(pending)
        """,
    ),
    (
        # Rule 25 again: a gate-annotated lock held across a device
        # drain — jax.device_get blocks on the accelerator stream. The
        # good twin snapshots the reference under the gate and drains
        # after releasing it.
        "blocking-call-under-dispatch-lock",
        """
        import threading
        import jax

        class DrainGate:
            def __init__(self):
                self._drain_gate = threading.Lock()  # graftlock: gate
                self._buf = None

            def drain(self):
                with self._drain_gate:
                    return jax.device_get(self._buf)
        """,
        """
        import threading
        import jax

        class DrainGate:
            def __init__(self):
                self._drain_gate = threading.Lock()  # graftlock: gate
                self._buf = None

            def drain(self):
                with self._drain_gate:
                    buf = self._buf
                    self._buf = None
                return jax.device_get(buf)
        """,
    ),
    (
        # Rule 26: a timer armed while a lock is held whose callback
        # re-acquires the same lock — if the timer can fire
        # synchronously (or the armer joins it) this deadlocks. The
        # good twin arms the timer after releasing the lock.
        "lock-released-across-await-seam",
        """
        import threading

        class Beat:
            def __init__(self):
                self._beat_lock = threading.Lock()
                self.beats = 0

            def arm(self):
                with self._beat_lock:
                    t = threading.Timer(1.0, self._fire)
                    t.start()

            def _fire(self):
                with self._beat_lock:
                    self.beats += 1
        """,
        """
        import threading

        class Beat:
            def __init__(self):
                self._beat_lock = threading.Lock()
                self.beats = 0

            def arm(self):
                with self._beat_lock:
                    interval = 1.0 + self.beats
                t = threading.Timer(interval, self._fire)
                t.start()

            def _fire(self):
                with self._beat_lock:
                    self.beats += 1
        """,
    ),
    (
        # Rule 26 again: an executor submit under the refresh lock
        # whose task transitively re-acquires it one call deep. The
        # good twin submits after the lock is released.
        "lock-released-across-await-seam",
        """
        import threading

        class Loader:
            def __init__(self, pool):
                self._refresh_lock = threading.Lock()
                self._pool = pool
                self.step = 0

            def kick(self):
                with self._refresh_lock:
                    self._pool.submit(self._reload)

            def _reload(self):
                self._commit()

            def _commit(self):
                with self._refresh_lock:
                    self.step += 1
        """,
        """
        import threading

        class Loader:
            def __init__(self, pool):
                self._refresh_lock = threading.Lock()
                self._pool = pool
                self.step = 0

            def kick(self):
                with self._refresh_lock:
                    stale = self.step
                if stale >= 0:
                    self._pool.submit(self._reload)

            def _reload(self):
                self._commit()

            def _commit(self):
                with self._refresh_lock:
                    self.step += 1
        """,
    ),
    (
        # blocking-transfer-in-actor-loop: a device_get + a method-
        # spelled block_until_ready inside the actor lane's while loop —
        # one sync per rollout on the acting critical path. The good
        # twin hands the device tree to the transfer-queue seam (method
        # calls are deliberately not followed: the queue's enqueue-time
        # device_put is the sanctioned off-critical-path home) and the
        # same calls OUTSIDE an actor/transfer scope stay clean.
        "blocking-transfer-in-actor-loop",
        """
        import jax

        def actor_loop(program, queue, bus, stop):
            state = None
            while not stop.is_set():
                version, params = bus.latest()
                state, batch = program(params, state)
                batch.block_until_ready()  # actor idles out the device
                queue.put(jax.device_get(batch), version)  # host round trip
        """,
        """
        import jax

        def actor_loop(program, queue, bus, stop):
            state = None
            while not stop.is_set():
                version, params = bus.latest()
                state, batch = program(params, state)
                queue.put(batch, version)  # device tree; the queue places it

        def drain(chunks):
            stacks = [c for c in chunks]
            return jax.device_get(stacks)  # learner-side amortized drain
        """,
    ),
    (
        # Same hazard one local hop deep: the transfer worker's for-loop
        # calls a same-module helper that device_puts per item. The good
        # twin keeps an IDENTICAL loop+helper under a name outside the
        # actor/transfer convention (the learner's drain loop) — the
        # rule is scoped to acting/transfer lanes, not to every loop.
        "blocking-transfer-in-actor-loop",
        """
        import jax

        def _place(item, device):
            return jax.device_put(item, device)

        def transfer_worker(items, device, out):
            for item in items:
                out.append(_place(item, device))  # upload per item
        """,
        """
        import jax

        def _place(item, device):
            return jax.device_put(item, device)

        def learner_drain(items, device, out):
            for item in items:
                out.append(_place(item, device))
        """,
    ),
    (
        "env-contract-impurity",
        """
        import numpy as np

        def step(state, velocity, params):
            noise = np.random.normal(size=velocity.shape)  # host RNG
            return state, velocity + noise
        """,
        """
        import jax, jax.numpy as jnp
        import numpy as np

        def step(state, velocity, params):
            key, k = jax.random.split(state.key)
            noise = jax.random.normal(k, velocity.shape)
            return state.replace(key=key), velocity + noise

        def make_table():
            # host RNG OUTSIDE the env contract surface: allowed
            return np.random.normal(size=(4,))
        """,
    ),
    (
        "env-contract-impurity",
        """
        _EPISODES = 0

        def reset(key, params):
            global _EPISODES  # trace-time rebind
            _EPISODES += 1
            return _EPISODES
        """,
        """
        import random
        from jax import random as jrandom

        def reset(key, params):
            # `random` here is jax.random under an alias: allowed
            return jrandom.uniform(key, (params.num_agents, 2))

        def pick_seed():
            return random.randint(0, 100)  # host code path: allowed
        """,
    ),
    (
        # Rule 24, tenancy-flavored: per-lane request counters shared
        # between a submitting caller and a background drain thread
        # (the serving/tenancy/fleet.py shape). The bad twin bumps the
        # lane's tally outside its annotated lock; the good twin holds
        # it.
        "unguarded-shared-mutation",
        """
        import threading

        class LaneCounters:
            def __init__(self, lanes):
                self._count_lock = threading.Lock()
                self.requests = dict()  # graftlock: guarded-by=_count_lock
                for mid in lanes:
                    self.requests[mid] = 0

            def start(self):
                threading.Thread(target=self._drain, daemon=True).start()

            def _drain(self):
                self.requests = {mid: 0 for mid in self.requests}
        """,
        """
        import threading

        class LaneCounters:
            def __init__(self, lanes):
                self._count_lock = threading.Lock()
                self.requests = dict()  # graftlock: guarded-by=_count_lock
                for mid in lanes:
                    self.requests[mid] = 0

            def start(self):
                threading.Thread(target=self._drain, daemon=True).start()

            def _drain(self):
                with self._count_lock:
                    self.requests = {mid: 0 for mid in self.requests}
        """,
    ),
]


@pytest.mark.parametrize(
    "rule,bad,good",
    FIXTURES,
    ids=[f"{r}-{i}" for i, (r, _, _) in enumerate(FIXTURES)],
)
def test_rule_fires_on_bad_and_not_on_good(rule, bad, good):
    assert rule in fired(bad), f"{rule} must fire on its known-bad fixture"
    assert rule not in fired(good), (
        f"{rule} must stay quiet on its known-good fixture: "
        f"{[str(v) for v in lint(good)]}"
    )


def test_every_rule_has_a_fixture():
    covered = {r for r, _, _ in FIXTURES}
    assert covered == set(rule_names())


# ---------------------------------------------------------------------------
# The package itself is clean — the acceptance gate.
# ---------------------------------------------------------------------------


def test_package_is_clean_at_default_severity():
    from marl_distributedformation_tpu.analysis import load_config

    violations = lint_paths([PACKAGE], load_config(REPO), root=REPO)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_package_scan_covers_serving():
    """The zero-violation pin must include the serving/ subsystem AND
    its fleet/ subpackage (a future exclude entry or package move
    cannot silently drop either)."""
    from marl_distributedformation_tpu.analysis import load_config
    from marl_distributedformation_tpu.analysis.linter import iter_python_files

    files = list(iter_python_files([PACKAGE], load_config(REPO), root=REPO))
    served = [f for f in files if "serving" in f.parts]
    assert len(served) >= 6, f"serving/ missing from the lint scan: {files}"
    fleet = [f for f in served if "fleet" in f.parts]
    assert len(fleet) >= 6, f"serving/fleet/ missing from the scan: {served}"
    mesh = [f for f in served if "mesh" in f.parts]
    assert len(mesh) >= 6, (
        f"serving/mesh/ missing from the scan (rule 21's subject must "
        f"itself stay pinned at 0): {served}"
    )


def test_package_scan_covers_tenancy():
    """The zero-violation pin must include serving/tenancy/ — the
    multi-tenant lane layer mutates shared per-lane counters from
    client threads and arms coordinators per lane, exactly the shapes
    rules 24/25 police; an exclude entry or package move cannot
    silently drop it from the scan."""
    from marl_distributedformation_tpu.analysis import load_config
    from marl_distributedformation_tpu.analysis.linter import iter_python_files

    files = list(iter_python_files([PACKAGE], load_config(REPO), root=REPO))
    tenancy = {f.name for f in files if "tenancy" in f.parts}
    assert {"directory.py", "fleet.py", "smoke.py"} <= tenancy, (
        f"serving/tenancy/ missing from the lint scan: {tenancy}"
    )


def test_package_scan_covers_elastic():
    """The zero-violation pin must include serving/elastic/ — the
    capacity controller mutates router topology from a background
    thread under the same locks the fleet's client threads take,
    exactly the cross-thread shapes the lock-discipline rules police;
    an exclude entry or package move cannot silently drop it from the
    scan."""
    from marl_distributedformation_tpu.analysis import load_config
    from marl_distributedformation_tpu.analysis.linter import iter_python_files

    files = list(iter_python_files([PACKAGE], load_config(REPO), root=REPO))
    elastic = {f.name for f in files if "elastic" in f.parts}
    assert {"__init__.py", "controller.py"} <= elastic, (
        f"serving/elastic/ missing from the lint scan: {elastic}"
    )


def test_package_scan_covers_train_modules():
    """The zero-violation pin must include every train/ module (the
    fused-scan trainer is the hottest scan in the repo — exactly where
    callback-in-hot-loop and the donation/scan rules earn their keep)
    plus the scenario schedule the fused chunk samples from."""
    from marl_distributedformation_tpu.analysis import load_config
    from marl_distributedformation_tpu.analysis.linter import iter_python_files

    files = list(iter_python_files([PACKAGE], load_config(REPO), root=REPO))
    train = {f.name for f in files if "train" in f.parts}
    assert {
        "trainer.py", "sweep.py", "curriculum.py", "hetero_sweep.py",
    } <= train, f"train/ modules missing from the lint scan: {train}"
    scenarios = {f.name for f in files if "scenarios" in f.parts}
    assert "schedule.py" in scenarios, (
        f"scenarios/schedule.py missing from the scan: {scenarios}"
    )


def test_package_scan_covers_analysis_engine():
    """The zero-violation pin must include the analysis package itself
    — the call-graph engine walks every other plane's locks, so its own
    source stays under the same discipline it enforces."""
    from marl_distributedformation_tpu.analysis import load_config
    from marl_distributedformation_tpu.analysis.linter import iter_python_files

    files = list(iter_python_files([PACKAGE], load_config(REPO), root=REPO))
    analysis = {f.name for f in files if "analysis" in f.parts}
    assert {"callgraph.py", "linter.py", "graftlock.py"} <= analysis, (
        f"analysis/ engine missing from the lint scan: {analysis}"
    )


def test_package_scan_covers_envs():
    """The zero-violation pin must include the envs/ subsystem — the
    env-contract-impurity rule's subject (registered step/reset
    implementations) lives there, and a future exclude entry cannot
    silently drop it from the scan."""
    from marl_distributedformation_tpu.analysis import load_config
    from marl_distributedformation_tpu.analysis.linter import iter_python_files

    files = list(iter_python_files([PACKAGE], load_config(REPO), root=REPO))
    envs = {f.name for f in files if "envs" in f.parts}
    assert {
        "spec.py", "registry.py", "formation.py", "pursuit.py",
    } <= envs, f"envs/ missing from the lint scan: {envs}"
    legacy = {f.name for f in files if "env" in f.parts}
    assert "formation.py" in legacy, (
        f"legacy env/ missing from the scan: {legacy}"
    )


def test_package_scan_covers_obs_instrumented_seams():
    """The zero-violation pin must include the tracing spine and the
    subsystems it instruments — rule 15 (span-in-traced-scope) only
    protects the budget-1 receipts if the files recording spans are in
    the scan."""
    from marl_distributedformation_tpu.analysis import load_config
    from marl_distributedformation_tpu.analysis.linter import iter_python_files

    files = list(iter_python_files([PACKAGE], load_config(REPO), root=REPO))
    obs = {f.name for f in files if "obs" in f.parts}
    assert {"tracer.py", "export.py", "flightrec.py"} <= obs, (
        f"obs/ missing from the lint scan: {obs}"
    )
    pipeline = {f.name for f in files if "pipeline" in f.parts}
    assert {"gate.py", "supervisor.py"} <= pipeline, (
        f"pipeline/ missing from the lint scan: {pipeline}"
    )


# ---------------------------------------------------------------------------
# Suppression + config machinery
# ---------------------------------------------------------------------------


def test_same_line_suppression():
    src = """
    import jax

    @jax.jit
    def f(x):
        print(x)  # graftlint: disable=print-in-jit
        return x
    """
    assert "print-in-jit" not in fired(src)


def test_comment_above_suppression():
    src = """
    import jax

    @jax.jit
    def f(x):
        # graftlint: disable=print-in-jit — tracing breadcrumb, deliberate
        print(x)
        return x
    """
    assert "print-in-jit" not in fired(src)


def test_file_level_suppression():
    src = """
    # graftlint: disable-file=print-in-jit
    import jax

    @jax.jit
    def f(x):
        print(x)
        return x
    """
    assert "print-in-jit" not in fired(src)


def test_suppression_is_rule_specific():
    src = """
    import jax

    @jax.jit
    def f(x):
        print(float(x))  # graftlint: disable=print-in-jit
        return x
    """
    rules = fired(src)
    assert "print-in-jit" not in rules
    assert "host-sync-in-jit" in rules, "other rules must survive"


def test_shim_module_needs_its_suppression():
    """jax_compat.py spells the legacy import on purpose; without its
    inline disable the deprecated-api rule must flag it (proves the
    suppression there is load-bearing, not decorative)."""
    shim = (PACKAGE / "jax_compat.py").read_text()
    assert "graftlint: disable=deprecated-api" in shim
    stripped = shim.replace("# graftlint: disable=deprecated-api", "#")
    violations = lint_source(stripped, "jax_compat.py")
    assert any(v.rule == "deprecated-api" for v in violations)


def test_suppression_prose_cannot_name_other_rules():
    """The payload ends at the first non-rule token: prose after the
    suppressed rule may mention other rules by name without silencing
    them."""
    src = """
    import jax

    @jax.jit
    def f(x):
        print(float(x))  # graftlint: disable=print-in-jit unlike host-sync-in-jit this is fine
        return x
    """
    rules = fired(src)
    assert "print-in-jit" not in rules
    assert "host-sync-in-jit" in rules


def test_config_defaults_without_toml_parser(monkeypatch):
    """py3.10 with runtime-only deps has no TOML parser; load_config must
    degrade to all-default severities instead of crashing the CLI."""
    import builtins
    import sys

    from marl_distributedformation_tpu.analysis import load_config

    monkeypatch.delitem(sys.modules, "tomllib", raising=False)
    monkeypatch.delitem(sys.modules, "tomli", raising=False)
    real_import = builtins.__import__

    def no_toml(name, *args, **kwargs):
        if name in ("tomllib", "tomli"):
            raise ImportError(name)
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", no_toml)
    config = load_config(REPO)
    assert config == GraftlintConfig()


def test_severity_override_and_off():
    bad = """
    import jax

    @jax.jit
    def f(x):
        print(x)
        return x
    """
    config = config_from_dict({"severity": {"print-in-jit": "warn"}})
    vs = lint_source(textwrap.dedent(bad), "f.py", config)
    assert [v.severity for v in vs if v.rule == "print-in-jit"] == ["warn"]
    config_off = config_from_dict({"severity": {"print-in-jit": "off"}})
    assert lint_source(textwrap.dedent(bad), "f.py", config_off) == []


def test_exclude_list(tmp_path):
    (tmp_path / "skipme").mkdir()
    bad = "import jax\n\n@jax.jit\ndef f(x):\n    print(x)\n    return x\n"
    (tmp_path / "skipme" / "mod.py").write_text(bad)
    (tmp_path / "mod.py").write_text(bad)
    config = config_from_dict({"exclude": ["skipme"]})
    vs = lint_paths([tmp_path], config, root=tmp_path)
    assert {Path(v.path).parent.name for v in vs} == {tmp_path.name}


def test_pyproject_config_block_parses():
    """The repo's own [tool.graftlint] block loads through the real
    parser (a typo'd severity would otherwise only explode in CI)."""
    from marl_distributedformation_tpu.analysis import load_config

    config = load_config(REPO)
    for rule in rule_names():
        assert config.rule_severity(rule, "error") in ("error", "warn", "off")


def test_syntax_error_reported_not_raised():
    vs = lint_source("def broken(:\n", "bad.py")
    assert [v.rule for v in vs] == ["syntax-error"]


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def test_cli_check_passes_on_package():
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "graftlint.py"), "--check"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 error(s)" in out.stdout


def test_cli_survives_broken_tree_and_skips_jax(tmp_path):
    """The CLI is pure-AST: a syntax-broken tree must produce the
    dedicated syntax-error violation (exit 1 under --check), not an
    import traceback — and linting must never start a jax session (the
    stub-package import path in scripts/graftlint.py)."""
    (tmp_path / "broken.py").write_text("def broken(:\n")
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "graftlint.py"),
            "--check",
            str(tmp_path),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    assert "syntax-error" in out.stdout
    assert "Traceback" not in out.stderr
    # jax stays unimported for the whole CLI run.
    probe_code = (
        "import sys, runpy\n"
        f"sys.argv = ['graftlint', {str(tmp_path / 'broken.py')!r}]\n"
        "try:\n"
        f"    runpy.run_path({str(REPO / 'scripts' / 'graftlint.py')!r},"
        " run_name='__main__')\n"
        "except SystemExit:\n"
        "    pass\n"
        "print('jax-imported' if 'jax' in sys.modules else 'jax-not-imported')\n"
    )
    probe = subprocess.run(
        [sys.executable, "-c", probe_code],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert "jax-not-imported" in probe.stdout, probe.stdout + probe.stderr


def test_cli_check_fails_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    return float(x)\n"
    )
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "graftlint.py"),
            "--check",
            str(bad),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    assert "host-sync-in-jit" in out.stdout


def test_cli_sarif_output_shape(tmp_path):
    """--format sarif emits a SARIF 2.1.0 document: schema + version,
    the full rule catalogue in the driver, and per-result ruleId /
    level / physical location. A lock-ordering result's message must
    carry the complete acquisition chain."""
    (tmp_path / "cycle.py").write_text(
        textwrap.dedent(
            """
            import threading

            class Pool:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()
                    self.c_lock = threading.Lock()

                def ab(self):
                    with self.a_lock:
                        with self.b_lock:
                            pass

                def bc(self):
                    with self.b_lock:
                        with self.c_lock:
                            pass

                def ca(self):
                    with self.c_lock:
                        with self.a_lock:
                            pass
            """
        )
    )
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "graftlint.py"),
            "--format",
            "sarif",
            str(tmp_path),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)  # stdout is ONLY the document
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    ids = [r["id"] for r in driver["rules"]]
    assert ids == rule_names()
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]
        assert r["defaultConfiguration"]["level"] in ("error", "warning")
    results = run["results"]
    assert results, "the seeded cycle must produce at least one result"
    by_rule = {r["ruleId"]: r for r in results}
    cycle = by_rule["lock-ordering-cycle"]
    assert cycle["level"] == "error"
    assert cycle["ruleIndex"] == ids.index("lock-ordering-cycle")
    text = cycle["message"]["text"]
    # Full acquisition chain: all three edges, each with its site.
    assert text.count("holding") == 3
    for lock in ("a_lock", "b_lock", "c_lock"):
        assert lock in text
    assert "cycle.py:" in text
    loc = cycle["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("cycle.py")
    assert loc["region"]["startLine"] >= 1
    assert loc["region"]["startColumn"] >= 1
