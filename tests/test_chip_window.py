"""Orchestration tests for the chip-window burster (scripts/chip_window.sh).

The burster carries the round's hardware-evidence workflow (stamp-based
resume across short tunnel windows); its logic must hold without a chip.
``CHIP_PROBE_CMD`` substitutes the device probe and ``CHIP_STATE_DIR`` /
``CHIP_LOCK_FILE`` isolate the run from a live watchdog, so these pin:

- tunnel-down => clean exit before any stage;
- all stages pre-stamped + tunnel up => ALL_DONE sentinel written and no
  stage re-runs (resume semantics);
- lock contention => exit 73 without touching state.
"""

from __future__ import annotations

import pathlib
import subprocess

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "chip_window.sh"

# Stage names as chip_window.sh defines them, plus the per-path smoke
# stamps derived from tpu_smoke.py --list.
# Round-5 order (VERDICT r4 next-#2): the monolithic full bench runs
# FIRST after parity so the shipped tree gets a driver-grade chip record
# under the retuned batch-16384 preset at the earliest window, instead of
# the round-4 tail position that left BENCH_r04.json a CPU fallback.
STAGES = [
    "parity", "bench", "knn_big", "bench_train", "bench_knn", "smoke",
    "profile", "tuning", "sweep_bench", "knn_big_tuning",
    "gnn1024_learn", "hetero5", "hetero5_eval", "sweep8",
]


def run_burster(tmp_path, probe_cmd: str, timeout: int = 120,
                path: str = "/usr/bin:/bin:/usr/local/bin"):
    env = {
        "PATH": path,
        "HOME": str(tmp_path),
        "CHIP_PROBE_CMD": probe_cmd,
        # A live watchdog's bench child (or another test's bench.py
        # subprocess) must not defer THIS isolated run.
        "CHIP_FOREIGN_BENCH_CMD": "false",
        "CHIP_STATE_DIR": str(tmp_path / "state"),
        "CHIP_LOCK_FILE": str(tmp_path / "lock"),
    }
    return subprocess.run(
        ["bash", str(SCRIPT)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO,
    )


def smoke_paths() -> list[str]:
    out = subprocess.run(
        ["python", str(REPO / "scripts" / "tpu_smoke.py"), "--list"],
        capture_output=True, text=True, check=True, cwd=REPO,
    )
    return out.stdout.split()


def test_tunnel_down_exits_before_any_stage(tmp_path):
    res = run_burster(tmp_path, "false")
    assert res.returncode == 0, res.stderr
    assert "tunnel down, nothing to do" in res.stdout
    assert "== stage" not in res.stdout
    state = tmp_path / "state"
    assert not any(state.iterdir()), list(state.iterdir())


def test_all_stamped_resumes_to_all_done(tmp_path):
    state = tmp_path / "state"
    state.mkdir()
    for s in STAGES:
        (state / s).touch()
    for p in smoke_paths():
        (state / f"smoke_{p}").touch()
    res = run_burster(tmp_path, "true")
    assert res.returncode == 0, res.stderr
    # Every stage was stamped => nothing runs, sentinel appears.
    assert "== stage" not in res.stdout
    assert "ALL stages stamped" in res.stdout
    assert (state / "ALL_DONE").exists()


def test_new_smoke_path_reopens_smoke_stamp(tmp_path):
    """Adding a path to tpu_smoke.py must reopen a stamped smoke stage —
    the aggregate stamp is only valid while every per-path stamp exists.
    The reconciliation is pure local state, so it runs even on a
    tunnel-down tick (probe stubbed false here)."""
    state = tmp_path / "state"
    state.mkdir()
    (state / "smoke").touch()
    (state / "ALL_DONE").touch()  # stale: must be reopened with it
    paths = smoke_paths()
    for p in paths[:-1]:  # the "new" path has no stamp yet
        (state / f"smoke_{p}").touch()
    res = run_burster(tmp_path, "false")
    assert res.returncode == 0, res.stderr
    assert not (state / "smoke").exists()
    assert not (state / "ALL_DONE").exists()
    # A fully-stamped path set must NOT reopen.
    (state / f"smoke_{paths[-1]}").touch()
    (state / "smoke").touch()
    res = run_burster(tmp_path, "false")
    assert res.returncode == 0, res.stderr
    assert (state / "smoke").exists()


def test_unstamped_stage_reopens_stale_all_done(tmp_path):
    """A grown stage list must clear a stale ALL_DONE sentinel —
    otherwise the watchdog short-circuits every tick and a newly added
    stage silently never runs. The unstamped stage is made to fail
    instantly by shadowing `python` with an exit-1 stub at the head of
    PATH (probe stays stubbed up) — shadowing, not stripping, so the
    failure mode doesn't depend on whether the distro ships
    /usr/bin/python (python-is-python3). This pins the sentinel logic,
    not the stage itself."""
    stub_bin = tmp_path / "bin"
    stub_bin.mkdir()
    stub = stub_bin / "python"
    stub.write_text("#!/bin/sh\nexit 1\n")
    stub.chmod(0o755)
    state = tmp_path / "state"
    state.mkdir()
    for s in STAGES:
        (state / s).touch()
    for p in smoke_paths():
        (state / f"smoke_{p}").touch()
    (state / "ALL_DONE").touch()
    (state / "profile").unlink()  # the queue grew / a stamp was cleared
    res = run_burster(tmp_path, "true", path=f"{stub_bin}:/usr/bin:/bin")
    assert res.returncode == 0, res.stderr
    assert "== stage profile " in res.stdout
    assert "ALL stages stamped" not in res.stdout
    assert not (state / "ALL_DONE").exists()
    # The sentinel only reopens; banked stamps stay banked.
    assert (state / "bench").exists()


def test_stage_list_in_sync_with_script():
    """STAGES above must match the stage() calls in the script — the
    same no-drifting-copy rule the script enforces for smoke paths."""
    text = SCRIPT.read_text()
    import re

    called = re.findall(r"^stage (\w+) ", text, re.MULTILINE)
    assert called == STAGES, (called, STAGES)


def test_lock_contention_exits_73(tmp_path):
    lock = tmp_path / "lock"
    holder = subprocess.Popen(
        ["flock", str(lock), "-c", "sleep 30"],
    )
    try:
        import time

        time.sleep(0.5)
        res = run_burster(tmp_path, "true")
        assert res.returncode == 73, (res.returncode, res.stdout, res.stderr)
        state = tmp_path / "state"
        assert not (state / "ALL_DONE").exists()
    finally:
        holder.kill()
        holder.wait()


def test_check_bench_record_gates():
    """The shared evidence gate (scripts/check_bench_record.py) rejects
    fallback/error/degraded records and missing fields, passes clean ones."""
    import sys

    sys.path.insert(0, str(REPO / "scripts"))
    try:
        from check_bench_record import check
    finally:
        sys.path.pop(0)

    clean = {
        "metric": "m", "platform": "tpu", "value": 1.0,
        "knn_impl": "pallas", "knn_env_steps_per_sec": 5.0,
    }
    assert check(clean, ["value", "knn_env_steps_per_sec"],
                 ["knn_impl=pallas"]) == []
    assert check({**clean, "fallback": True}, [], [])
    assert check({**clean, "platform": "cpu"}, [], [])
    assert check({**clean, "error": "watchdog"}, [], [])
    assert check({**clean, "notes": "train phase skipped: deadline"}, [], [])
    assert check({**clean, "notes": "knn phase failed: X"}, [], [])
    assert check(clean, ["train_env_steps_per_sec"], [])  # absent field
    assert check({**clean, "value": 0.0}, ["value"], [])  # zero rate
    assert check(clean, [], ["knn_impl=xla"])  # impl mismatch
    # Obs tracing fields (bench phase 8), validated whenever present:
    # overhead must be a finite number; the span breakdown must be a
    # numeric stage dict whose sum stays within the latency + tolerance.
    assert check({**clean, "tracing_overhead_pct": 1.7}, [], []) == []
    assert check({**clean, "tracing_overhead_pct": -0.4}, [], []) == []
    assert check({**clean, "tracing_overhead_pct": float("inf")}, [], [])
    assert check({**clean, "tracing_overhead_pct": "fast"}, [], [])
    pipeline_ok = {
        **clean,
        "promotion_latency_s_p50": 2.0, "promotion_latency_s_p95": 3.0,
        "gate_eval_steps_per_sec": 100.0, "pipeline_gate_compiles": 1,
    }
    breakdown = {
        "stream_poll_s": 1.0, "gate_eval_s": 0.8, "publish_s": 0.01,
        "barrier_commit_s": 0.15, "first_serve_s": 0.04,
    }
    assert check(
        {**pipeline_ok, "promotion_span_breakdown": breakdown}, [], []
    ) == []
    assert check(  # stages sum past p95 + tolerance: double counting
        {**pipeline_ok,
         "promotion_span_breakdown": {**breakdown, "stream_poll_s": 9.0}},
        [], [],
    )
    # deferred_wait_s is p50'd over ONLY deferred promotions — a few
    # long defers among many fast promotions may dwarf the all-promotion
    # latency p95 on a healthy run, so it stays out of the sum check.
    assert check(
        {**pipeline_ok,
         "promotion_span_breakdown": {**breakdown, "deferred_wait_s": 30.0}},
        [], [],
    ) == []
    assert check(
        {**pipeline_ok, "promotion_span_breakdown": {}}, [], []
    )
    assert check(
        {**pipeline_ok,
         "promotion_span_breakdown": {"gate_eval_s": "slow"}},
        [], [],
    )
    assert check(
        {**pipeline_ok,
         "promotion_span_breakdown": {"gate_eval_s": -1.0}},
        [], [],
    )
    # SLO serving fields (bench phase 9), validated whenever the
    # req/s-at-SLO headline is present: positive rate and 512-rung
    # percentiles, finite bf16 delta (negative legitimate on CPU),
    # budget-1 compile receipts.
    slo_ok = {
        **clean,
        "serving_req_per_sec_at_p95_slo": 462.0,
        "serving_sharded_512_p95_ms": 27.7,
        "serving_replicated_512_p95_ms": 57.3,
        "serving_bf16_speedup_pct": -20.0,
        "serving_slo_max_compiles_per_rung": 1,
    }
    assert check(slo_ok, [], []) == []
    assert check({**slo_ok, "serving_req_per_sec_at_p95_slo": 0.0}, [], [])
    assert check({**slo_ok, "serving_sharded_512_p95_ms": 0.0}, [], [])
    assert check(
        {**slo_ok, "serving_bf16_speedup_pct": float("nan")}, [], []
    )
    assert check(
        {**slo_ok, "serving_slo_max_compiles_per_rung": 2}, [], []
    )
    # Adversarial-robustness fields (bench phase 10), validated whenever
    # the search throughput is present: positive rate, budget-1 search
    # compiles, finite worst-case gap (negative legitimate — bench-sized
    # training makes the curriculum payoff directional).
    adv_ok = {
        **clean,
        "adversarial_candidates_per_sec": 42.0,
        "adversarial_search_compiles": 1,
        "worst_case_return_gap_pct": 5.2,
    }
    assert check(adv_ok, [], []) == []
    assert check({**adv_ok, "worst_case_return_gap_pct": -3.0}, [], []) == []
    assert check({**adv_ok, "adversarial_candidates_per_sec": 0.0}, [], [])
    assert check({**adv_ok, "adversarial_search_compiles": 2}, [], [])
    assert check(
        {**adv_ok, "worst_case_return_gap_pct": float("nan")}, [], []
    )
    assert check(
        {**adv_ok, "worst_case_return_gap_pct": "better"}, [], []
    )
    # BENCH_SKIP_* sentinel: "skipped" in a rate field is structurally
    # absent (no SLO validation fires), but --require rejects it with
    # the explicit not-run reason instead of a generic type error.
    skipped = {**clean, "serving_req_per_sec_at_p95_slo": "skipped"}
    assert check(skipped, [], []) == []
    problems = check(skipped, ["serving_req_per_sec_at_p95_slo"], [])
    assert problems and "explicitly skipped" in problems[0]
    adv_skipped = {
        **clean,
        "adversarial_candidates_per_sec": "skipped",
        "adversarial_search_compiles": "skipped",
        "worst_case_return_gap_pct": "skipped",
    }
    assert check(adv_skipped, [], []) == []
    # Live-metrics-plane fields (bench phase 11), validated whenever
    # present: finite telemetry overhead (negative legitimate — noise
    # around zero is the expected result), positive sentinel poll rate,
    # "skipped" sentinels structurally absent.
    tel_ok = {
        **clean,
        "telemetry_overhead_pct": -0.1,
        "sentinel_checks_per_sec": 87488.7,
    }
    assert check(tel_ok, [], []) == []
    assert check({**tel_ok, "telemetry_overhead_pct": float("nan")}, [], [])
    assert check({**tel_ok, "telemetry_overhead_pct": "cheap"}, [], [])
    assert check({**tel_ok, "sentinel_checks_per_sec": 0.0}, [], [])
    assert check({**tel_ok, "sentinel_checks_per_sec": "many"}, [], [])
    assert check(
        {
            **clean,
            "telemetry_overhead_pct": "skipped",
            "sentinel_checks_per_sec": "skipped",
        },
        [], [],
    ) == []
    # Chaos-plane fields (bench phase 12), validated whenever present:
    # violations must be exactly 0, MTTR finite and > 0, the
    # disabled-plane overhead finite and under the 5% bar (negative is
    # legitimate — noise around zero), "skipped" sentinels honored.
    chaos_ok = {
        **clean,
        "chaos_invariant_violations": 0,
        "chaos_mttr_s": 0.8,
        "fault_plane_overhead_pct": -0.2,
    }
    assert check(chaos_ok, [], []) == []
    assert check({**chaos_ok, "chaos_invariant_violations": 1}, [], [])
    assert check({**chaos_ok, "chaos_invariant_violations": "none"}, [], [])
    assert check({**chaos_ok, "chaos_mttr_s": 0.0}, [], [])
    assert check({**chaos_ok, "chaos_mttr_s": float("inf")}, [], [])
    assert check({**chaos_ok, "chaos_mttr_s": "fast"}, [], [])
    assert check({**chaos_ok, "fault_plane_overhead_pct": 7.5}, [], [])
    assert check(
        {**chaos_ok, "fault_plane_overhead_pct": float("nan")}, [], []
    )
    assert check(
        {
            **clean,
            "chaos_invariant_violations": "skipped",
            "chaos_mttr_s": "skipped",
            "fault_plane_overhead_pct": "skipped",
        },
        [], [],
    ) == []
    # Program-ledger fields (bench phase 13), validated whenever
    # present: enabled-ledger overhead finite and under the 5% bar
    # (negative legitimate — noise around zero), a census with at
    # least one program, finite non-negative total compile seconds,
    # "skipped" sentinels structurally absent.
    ledger_ok = {
        **clean,
        "ledger_overhead_pct": 0.8,
        "ledger_program_count": 11,
        "ledger_compile_seconds_total": 42.7,
    }
    assert check(ledger_ok, [], []) == []
    assert check({**ledger_ok, "ledger_overhead_pct": -0.3}, [], []) == []
    assert check({**ledger_ok, "ledger_overhead_pct": 6.1}, [], [])
    assert check(
        {**ledger_ok, "ledger_overhead_pct": float("inf")}, [], []
    )
    assert check({**ledger_ok, "ledger_overhead_pct": "cheap"}, [], [])
    assert check({**ledger_ok, "ledger_program_count": 0}, [], [])
    assert check({**ledger_ok, "ledger_program_count": "many"}, [], [])
    assert check(
        {**ledger_ok, "ledger_compile_seconds_total": -2.0}, [], []
    )
    assert check(
        {**ledger_ok, "ledger_compile_seconds_total": float("nan")},
        [], [],
    )
    assert check(
        {
            **clean,
            "ledger_overhead_pct": "skipped",
            "ledger_program_count": "skipped",
            "ledger_compile_seconds_total": "skipped",
        },
        [], [],
    ) == []
    # Mesh-tier fields (bench phase 14), validated whenever present:
    # throughput finite > 0, swap latency percentiles finite > 0 and
    # ordered, failover-lost EXACTLY 0, per-host compile receipts at
    # most 1, "skipped" sentinels honored.
    mesh_ok = {
        **clean,
        "mesh_req_per_sec": 412.0,
        "mesh_global_swap_latency_s_p50": 0.03,
        "mesh_global_swap_latency_s_p95": 0.09,
        "mesh_failover_lost_requests": 0,
        "mesh_host_compile_receipts_max": 1.0,
    }
    assert check(mesh_ok, [], []) == []
    assert check({**mesh_ok, "mesh_req_per_sec": 0.0}, [], [])
    assert check({**mesh_ok, "mesh_req_per_sec": "fast"}, [], [])
    assert check(
        {**mesh_ok, "mesh_global_swap_latency_s_p50": 0.0}, [], []
    )
    assert check(
        {**mesh_ok, "mesh_global_swap_latency_s_p95": float("inf")},
        [], [],
    )
    assert check(  # percentile order violated
        {
            **mesh_ok,
            "mesh_global_swap_latency_s_p50": 0.2,
            "mesh_global_swap_latency_s_p95": 0.1,
        },
        [], [],
    )
    assert check({**mesh_ok, "mesh_failover_lost_requests": 1}, [], [])
    assert check(
        {**mesh_ok, "mesh_failover_lost_requests": "none"}, [], []
    )
    assert check({**mesh_ok, "mesh_step_violations": 0}, [], []) == []
    assert check({**mesh_ok, "mesh_step_violations": 2}, [], [])
    assert check(
        {**mesh_ok, "mesh_host_compile_receipts_max": 2.0}, [], []
    )
    assert check(
        {
            **clean,
            "mesh_req_per_sec": "skipped",
            "mesh_global_swap_latency_s_p50": "skipped",
            "mesh_global_swap_latency_s_p95": "skipped",
            "mesh_failover_lost_requests": "skipped",
        },
        [], [],
    ) == []
    # Train-lane recovery fields (bench phase 15), validated whenever
    # present: health-word overhead finite under the 5% bar (negative
    # legitimate — interleave noise), recovery MTTR finite > 0, the
    # drill's divergence count >= 1 (the bench injects a bomb; zero
    # means the detector is broken), "skipped" sentinels honored.
    recovery_ok = {
        **clean,
        "health_overhead_pct": 0.7,
        "recovery_mttr_s": 0.21,
        "train_divergence_events": 1,
    }
    assert check(recovery_ok, [], []) == []
    assert check(
        {**recovery_ok, "health_overhead_pct": -0.2}, [], []
    ) == []
    assert check({**recovery_ok, "health_overhead_pct": 6.2}, [], [])
    assert check(
        {**recovery_ok, "health_overhead_pct": float("nan")}, [], []
    )
    assert check({**recovery_ok, "health_overhead_pct": "cheap"}, [], [])
    assert check({**recovery_ok, "recovery_mttr_s": 0.0}, [], [])
    assert check(
        {**recovery_ok, "recovery_mttr_s": float("inf")}, [], []
    )
    assert check({**recovery_ok, "recovery_mttr_s": "fast"}, [], [])
    assert check({**recovery_ok, "train_divergence_events": 0}, [], [])
    assert check(
        {**recovery_ok, "train_divergence_events": "some"}, [], []
    )
    assert check(
        {
            **clean,
            "health_overhead_pct": "skipped",
            "recovery_mttr_s": "skipped",
            "train_divergence_events": "skipped",
        },
        [], [],
    ) == []
    # graftlint wall (bench phase 16), validated whenever present:
    # finite positive and under the static ceiling (the engine's
    # package-global analyses must not go super-linear).
    assert check({**clean, "graftlint_wall_s": 4.7}, [], []) == []
    assert check({**clean, "graftlint_wall_s": 0.0}, [], [])
    assert check({**clean, "graftlint_wall_s": -1.0}, [], [])
    assert check({**clean, "graftlint_wall_s": float("nan")}, [], [])
    assert check({**clean, "graftlint_wall_s": float("inf")}, [], [])
    assert check({**clean, "graftlint_wall_s": 500.0}, [], [])
    assert check({**clean, "graftlint_wall_s": "slow"}, [], [])
    assert check({**clean, "graftlint_wall_s": "skipped"}, [], []) == []
    # Registered-env ladder fields (bench phase 1d), validated whenever
    # present: both per-env rates finite positive AND recorded together
    # (a lone rate means the ladder died mid-loop), obstacle overhead a
    # finite number in [0, 100], "skipped" sentinels honored.
    envs_ok = {
        **clean,
        "env_steps_per_sec_formation": 1.6e6,
        "env_steps_per_sec_pursuit_evasion": 1.5e6,
        "obstacle_overhead_pct": 12.3,
    }
    assert check(envs_ok, [], []) == []
    assert check({**envs_ok, "env_steps_per_sec_formation": 0.0}, [], [])
    assert check(
        {**envs_ok, "env_steps_per_sec_pursuit_evasion": "fast"}, [], []
    )
    lone = dict(envs_ok)
    del lone["env_steps_per_sec_pursuit_evasion"]
    assert check(lone, [], [])  # ladder died mid-loop
    assert check({**envs_ok, "obstacle_overhead_pct": -3.0}, [], [])
    assert check({**envs_ok, "obstacle_overhead_pct": 101.0}, [], [])
    assert check(
        {**envs_ok, "obstacle_overhead_pct": float("nan")}, [], []
    )
    assert check({**envs_ok, "obstacle_overhead_pct": "cheap"}, [], [])
    assert check(
        {
            **clean,
            "env_steps_per_sec_formation": "skipped",
            "env_steps_per_sec_pursuit_evasion": "skipped",
            "obstacle_overhead_pct": "skipped",
        },
        [], [],
    ) == []
    # Multi-tenant serving fields (serving/tenancy), validated whenever
    # present: isolation ratio finite >= 1 beside per-tenant rates,
    # every lane rate finite positive, per-lane step monotonicity
    # violations exactly 0, shared_rung_compiles EXACTLY 1 per
    # (arch, rung) — 0 = never warmed, 2+ = a lane retraced instead of
    # sharing the executable.
    tenancy_ok = {
        **clean,
        "tenant_isolation_p95_ratio": 1.4,
        "model_formation-a__requests_per_sec": 120.0,
        "model_formation-b__requests_per_sec": 115.0,
        "model_pursuit__requests_per_sec": 98.0,
        "model_formation-a__step_monotonic_violations": 0,
        "shared_rung_compiles": {
            "MLPActorCritic_h8x8_obs6_act2:rung1": 1,
            "MLPActorCritic_h8x8_obs6_act2:rung8": 1,
            "GNNActorCritic_h8x8_obs9_act2:rung1": 1,
        },
    }
    assert check(tenancy_ok, [], []) == []
    assert check(
        {**tenancy_ok, "tenant_isolation_p95_ratio": 0.3}, [], []
    )
    assert check(
        {**tenancy_ok, "tenant_isolation_p95_ratio": float("inf")}, [], []
    )
    assert check(
        {**tenancy_ok, "tenant_isolation_p95_ratio": "isolated"}, [], []
    )
    assert check(  # ratio with no lane rates beside it
        {**clean, "tenant_isolation_p95_ratio": 1.1}, [], []
    )
    assert check(
        {**tenancy_ok, "model_pursuit__requests_per_sec": 0.0}, [], []
    )
    assert check(
        {**tenancy_ok, "model_pursuit__requests_per_sec": "fast"}, [], []
    )
    assert check(
        {**tenancy_ok, "model_formation-a__step_monotonic_violations": 2},
        [], [],
    )
    assert check({**tenancy_ok, "shared_rung_compiles": {}}, [], [])
    assert check(
        {**tenancy_ok, "shared_rung_compiles": "one each"}, [], []
    )
    bad_shared = dict(tenancy_ok["shared_rung_compiles"])
    bad_shared["MLPActorCritic_h8x8_obs6_act2:rung1"] = 2  # retrace
    assert check(
        {**tenancy_ok, "shared_rung_compiles": bad_shared}, [], []
    )
    bad_shared["MLPActorCritic_h8x8_obs6_act2:rung1"] = 0  # never warmed
    assert check(
        {**tenancy_ok, "shared_rung_compiles": bad_shared}, [], []
    )
    # Skipped sentinels honored across the tenancy fields.
    assert check(
        {
            **clean,
            "tenant_isolation_p95_ratio": "skipped",
            "model_formation-a__requests_per_sec": "skipped",
            "shared_rung_compiles": "skipped",
        },
        [], [],
    ) == []
    # Elastic-capacity fields (serving/elastic, bench phase "elastic"),
    # validated whenever present: both storm-half rates finite
    # positive, the re-split pause bounded in (0, 250] ms beside a
    # committed re-split, prewarm compiles >= 1 beside a zero census
    # diff (every compile attributed to prewarm, never the request
    # path), budget-1 receipts per rung.
    elastic_ok = {
        **clean,
        "serving_req_per_sec_at_p95_slo_elastic": 1440.0,
        "serving_req_per_sec_at_p95_slo_static": 141.2,
        "elastic_resplit_pause_ms": 0.049,
        "elastic_resplits_committed": 2,
        "elastic_prewarm_compiles": 7,
        "elastic_storm_new_programs": 0,
        "elastic_max_compiles_per_rung": 1,
    }
    assert check(elastic_ok, [], []) == []
    assert check(
        {**elastic_ok, "serving_req_per_sec_at_p95_slo_elastic": 0.0},
        [], [],
    )
    assert check(
        {
            **elastic_ok,
            "serving_req_per_sec_at_p95_slo_static": float("nan"),
        },
        [], [],
    )
    assert check(
        {**elastic_ok, "serving_req_per_sec_at_p95_slo_elastic": "fast"},
        [], [],
    )
    assert check({**elastic_ok, "elastic_resplit_pause_ms": 0.0}, [], [])
    assert check(
        {**elastic_ok, "elastic_resplit_pause_ms": 900.0}, [], []
    )
    assert check(
        {**elastic_ok, "elastic_resplit_pause_ms": "quick"}, [], []
    )
    assert check(  # pause with nothing committed beside it
        {**elastic_ok, "elastic_resplits_committed": 0}, [], []
    )
    assert check({**elastic_ok, "elastic_prewarm_compiles": 0}, [], [])
    assert check(  # a compile leaked onto the measured storm path
        {**elastic_ok, "elastic_storm_new_programs": 3}, [], []
    )
    assert check(  # a rung retraced after warm-up
        {**elastic_ok, "elastic_max_compiles_per_rung": 2}, [], []
    )
    # Skipped sentinels honored across the elastic fields.
    assert check(
        {
            **clean,
            "serving_req_per_sec_at_p95_slo_elastic": "skipped",
            "serving_req_per_sec_at_p95_slo_static": "skipped",
            "elastic_resplit_pause_ms": "skipped",
            "elastic_prewarm_compiles": "skipped",
        },
        [], [],
    ) == []


def test_partial_mirror_names_dodge_replay_glob():
    """Partial-phase mirrors must NOT match the docs/acceptance/
    tpu_bench_r*.md glob bench.py's _latest_chip_bench_claim() reads as
    FULL-bench records for the CPU-fallback replay pointer."""
    text = SCRIPT.read_text()
    import fnmatch
    import re

    mirrors = re.findall(r"docs/acceptance/(\S+\.md)", text)
    assert mirrors, "burster no longer writes mirrors?"
    full = [m for m in mirrors if fnmatch.fnmatch(m, "tpu_bench_r*.md")]
    # Exactly the monolithic full-bench record may match the glob.
    assert full == ["tpu_bench_r5.md"], full
