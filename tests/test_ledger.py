"""Program ledger (obs/ledger.py + the analysis/guards.py seam).

The contract under test: every compile site registers exactly one
census entry per compilation (entry count == budget-1 receipt count),
cost/memory facts are present-or-explicitly-unavailable with the source
recorded, the disabled ledger is inert, dispatch histograms survive
writer-thread churn, the census renders as ``program{...}``-labeled
Prometheus families and round-trips through ``program_report.py``, the
census diff gate catches new/vanished/drifted programs, and the
RegressionSentinel's ledger watches trip the flightrec+audit machinery
on an inflated compile-time reading.
"""

import json
import sys
import threading
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.analysis.guards import (
    RetraceError,
    RetraceGuard,
    ledgered_jit,
    register_aot_program,
    sample_device_watermark,
)
from marl_distributedformation_tpu.obs.export import prometheus_exposition
from marl_distributedformation_tpu.obs.ledger import (
    ANALYSIS_SOURCES,
    CENSUS_SCHEMA,
    ProgramLedger,
    get_ledger,
    load_census,
    sanitize_key,
    set_ledger,
)

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture
def private_ledger():
    """A fresh process-global ledger per test, restored afterwards."""
    previous = set_ledger(ProgramLedger(enabled=True, reservoir=64))
    try:
        yield get_ledger()
    finally:
        set_ledger(previous)


def _record_invariants(rec):
    """Present-or-explicitly-unavailable: the record always says which
    analysis path produced (or failed to produce) its facts."""
    assert rec.analysis_source in ANALYSIS_SOURCES
    if rec.analysis_source in ("executable", "aot"):
        # Full facts: the compiled executable answered.
        assert rec.facts.get("argument_bytes") is not None
        assert rec.facts.get("temp_bytes") is not None
    elif rec.analysis_source == "lowered":
        # Pre-compile estimates: cost yes, memory footprint no.
        assert rec.facts.get("flops") is not None
    else:
        assert rec.analysis_error, (
            "an unavailable record must say why"
        )


# ---------------------------------------------------------------------------
# Core seam semantics
# ---------------------------------------------------------------------------


def test_sanitize_key():
    assert sanitize_key("Trainer.Train Iteration") == "trainer_train_iteration"
    assert sanitize_key("__x__") == "x"
    assert sanitize_key("???") == "program"


def test_disabled_ledger_is_inert(private_ledger):
    private_ledger.enabled = False
    guard = RetraceGuard("t", max_traces=1)
    fn = ledgered_jit(
        lambda x: x * 2.0, guard, subsystem="test", program="inert"
    )
    out = fn(jnp.ones((4,)))
    np.testing.assert_array_equal(np.asarray(out), 2.0 * np.ones(4))
    assert private_ledger.entries() == []
    assert private_ledger.snapshot() == {}
    assert (
        private_ledger.register(name="x", subsystem="y") is None
    )
    private_ledger.dispatch("x", 0.1)  # no-op, no crash
    private_ledger.record_watermark(123.0)
    assert private_ledger.snapshot() == {}
    assert sample_device_watermark(force=True) is None


def test_one_entry_per_compile_and_dispatch_histograms(private_ledger):
    guard = RetraceGuard("t", max_traces=1)
    fn = ledgered_jit(
        lambda x: jnp.tanh(x @ x).sum(),
        guard,
        subsystem="test",
        program="one_compile",
    )
    for _ in range(5):
        fn(jnp.ones((8, 8)))
    entries = private_ledger.entries()
    assert len(entries) == 1 == guard.count
    rec = entries[0]
    assert rec.key == "test_one_compile"
    assert rec.subsystem == "test"
    assert "float32[8,8]" in rec.fingerprint
    _record_invariants(rec)
    snap = private_ledger.snapshot()
    assert snap["ledger_programs_total"] == 1.0
    # Steady-state dispatches only: the compiling call is a build
    # event (first_dispatch_seconds), never a latency sample.
    assert snap["program_test_one_compile_dispatches_total"] == 4.0
    assert snap["program_test_one_compile_dispatch_seconds_count"] == 4.0
    assert snap["program_test_one_compile_dispatch_seconds_p50"] > 0.0
    assert snap["ledger_compile_seconds_total"] > 0.0
    # Build timings landed (monitoring attribution or first-call wall).
    assert rec.timings["first_dispatch_seconds"] > 0.0


def test_two_signatures_two_entries(private_ledger):
    guard = RetraceGuard("t")  # count-only
    fn = ledgered_jit(
        lambda x: x.sum(), guard, subsystem="test", program="poly"
    )
    fn(jnp.ones((4,)))
    fn(jnp.ones((16,)))
    fn(jnp.ones((16,)))
    entries = private_ledger.entries()
    assert len(entries) == 2 == guard.count
    assert {e.key for e in entries} == {"test_poly", "test_poly_2"}
    # One shared dispatch histogram under the stable wrapper key
    # (compiling calls excluded: 3 calls, 2 compiles, 1 dispatch).
    snap = private_ledger.snapshot()
    assert snap["program_test_poly_dispatches_total"] == 1.0


def test_results_bitwise_identical_ledger_on_off(private_ledger):
    def f(x):
        return jnp.sin(x @ x) + 0.5

    x = jnp.linspace(0.0, 1.0, 64, dtype=jnp.float32).reshape(8, 8)
    on = ledgered_jit(
        f, RetraceGuard("on"), subsystem="test", program="parity_on"
    )(x)
    private_ledger.enabled = False
    off = ledgered_jit(
        f, RetraceGuard("off"), subsystem="test", program="parity_off"
    )(x)
    np.testing.assert_array_equal(np.asarray(on), np.asarray(off))


def test_budget_still_enforced_and_failed_trace_unregistered(
    private_ledger,
):
    guard = RetraceGuard("t", max_traces=1)
    fn = ledgered_jit(
        lambda x: x * 3.0, guard, subsystem="test", program="budget"
    )
    fn(jnp.ones((4,)))
    with pytest.raises(RetraceError):
        fn(jnp.ones((5,)))  # shape drift: the budget must still fire
    # The over-budget ATTEMPT is counted (existing guard semantics)
    # but produced no program — the census stays at one entry.
    assert len(private_ledger.entries()) == 1


def test_donation_map_recorded(private_ledger):
    guard = RetraceGuard("t", max_traces=1)
    fn = ledgered_jit(
        lambda s, x: (s + x, x),
        guard,
        subsystem="test",
        program="donated",
        donate_argnums=(0,),
    )
    fn(jnp.zeros((4,)), jnp.ones((4,)))
    (rec,) = private_ledger.entries()
    assert rec.donate_argnums == (0,)


def test_dispatch_concurrency_and_dead_thread_fold(private_ledger):
    guard = RetraceGuard("t", max_traces=1)
    fn = ledgered_jit(
        lambda x: x + 1.0, guard, subsystem="test", program="threads"
    )
    fn(jnp.ones((4,)))  # compile once on the main thread
    per_thread, n_threads = 40, 5

    def worker():
        for _ in range(per_thread):
            fn(jnp.ones((4,)))

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Dead writer threads' shards fold into retired accumulators:
    # totals stay exact after every writer is gone.
    snap = private_ledger.snapshot()
    assert snap["program_test_threads_dispatches_total"] == float(
        per_thread * n_threads
    )
    assert snap["program_test_threads_dispatch_seconds_count"] == float(
        per_thread * n_threads
    )
    assert guard.count == 1 and len(private_ledger.entries()) == 1


def test_watermark_gauges(private_ledger):
    private_ledger.record_watermark(100.0)
    private_ledger.record_watermark(500.0)
    private_ledger.record_watermark(200.0)
    snap = private_ledger.snapshot()
    assert snap["device_memory_bytes_in_use"] == 200.0
    assert snap["device_memory_watermark_bytes"] == 500.0
    # The jax-side sampler answers on this backend and only raises the
    # watermark. Keep a device array alive so the CPU fallback (summed
    # live buffers) has something to count.
    keep = jnp.ones((128,))
    live = sample_device_watermark(force=True)
    del keep
    assert live is not None and live > 0.0
    assert (
        private_ledger.snapshot()["device_memory_watermark_bytes"]
        >= 500.0
    )


def test_aot_registration(private_ledger):
    def f(x):
        return (x * 2.0).sum()

    lowered = jax.jit(f).lower(jnp.ones((8,)))
    compiled = lowered.compile()
    key = register_aot_program(
        name="aot_prog",
        subsystem="test",
        compiled=compiled,
        fingerprint="f32[8]",
        timings={"lower_seconds": 0.01, "compile_seconds": 0.5},
    )
    assert key == "test_aot_prog"
    (rec,) = private_ledger.entries()
    assert rec.analysis_source == "aot"
    _record_invariants(rec)
    assert rec.timings["compile_seconds"] == 0.5
    private_ledger.dispatch(key, 0.002)
    snap = private_ledger.snapshot()
    assert snap["program_test_aot_prog_dispatches_total"] == 1.0


# ---------------------------------------------------------------------------
# Compile-site coverage: serving rungs + trainer/samplers
# ---------------------------------------------------------------------------


def test_serving_rungs_register(private_ledger):
    from marl_distributedformation_tpu.compat.policy import LoadedPolicy
    from marl_distributedformation_tpu.models import MLPActorCritic
    from marl_distributedformation_tpu.serving import BucketedPolicyEngine

    model = MLPActorCritic(act_dim=2, hidden=(16,))
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 6)))
    policy = LoadedPolicy(dict(variables), model_kwargs={"hidden": (16,)})
    engine = BucketedPolicyEngine(policy, buckets=(1, 4))
    obs = np.zeros((3, 6), np.float32)  # pads to rung 4
    engine.act(obs)
    engine.act(obs)  # steady-state dispatch on the warm rung
    engine.act(np.zeros((1, 6), np.float32))  # rung 1
    receipts = sum(engine.compile_counts().values())
    entries = private_ledger.entries()
    assert len(entries) == receipts == 2
    keys = {e.key for e in entries}
    assert keys == {"serving_act_rung1_f32", "serving_act_rung4_f32"}
    for rec in entries:
        _record_invariants(rec)
    snap = private_ledger.snapshot()
    assert snap["program_serving_act_rung4_f32_dispatches_total"] >= 1.0


def test_trainer_and_samplers_register(private_ledger, tmp_path):
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.scenarios import (
        ScenarioSchedule,
        ScenarioStage,
    )
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    trainer = Trainer(
        EnvParams(num_agents=3),
        ppo=PPOConfig(n_steps=8, batch_size=8, n_epochs=1),
        config=TrainConfig(
            num_formations=4,
            checkpoint=False,
            use_wandb=False,
            name="ledger_t",
            log_dir=str(tmp_path),
            guard_retraces=1,
        ),
        scenario_schedule=ScenarioSchedule(
            stages=(
                ScenarioStage(
                    rollouts=8, scenarios=("clean",), severity=0.0
                ),
            )
        ),
    )
    for _ in range(2):
        trainer.run_iteration()
    receipts = trainer.retrace_guard.count + trainer._sampler_guard.count
    entries = private_ledger.entries()
    assert len(entries) == receipts
    by_subsystem = {e.subsystem for e in entries}
    assert by_subsystem == {"trainer", "scenarios"}
    train_rec = next(e for e in entries if e.subsystem == "trainer")
    assert train_rec.donate_argnums == (0, 1)
    _record_invariants(train_rec)
    # The budget-1 receipt holds with the ledger ON.
    assert trainer.retrace_guard.count == 1
    snap = private_ledger.snapshot()
    assert (
        snap["program_trainer_train_iteration_dispatches_total"] == 1.0
    )


# ---------------------------------------------------------------------------
# TraceWindow capture audit
# ---------------------------------------------------------------------------


def test_trace_window_emits_capture_audit_line(private_ledger, tmp_path):
    from marl_distributedformation_tpu.utils.profiling import TraceWindow

    guard = RetraceGuard("t", max_traces=1)
    fn = ledgered_jit(
        lambda x: (x * 2.0).sum(),
        guard,
        subsystem="test",
        program="profiled",
    )
    window = TraceWindow(str(tmp_path), enabled=True, count=2, skip=1)
    for _ in range(4):
        window.before_dispatch()
        out = fn(jnp.ones((8,)))
        window.after_dispatch(out)
    assert window.captured
    audit = tmp_path / "profile" / TraceWindow.AUDIT_NAME
    assert audit.exists()
    (line,) = [
        json.loads(ln) for ln in audit.read_text().splitlines() if ln
    ]
    assert line["event"] == "profile_capture"
    assert line["completed"] is True
    assert line["dispatches_traced"] == 2
    assert line["trace_dir"].endswith("profile")
    # The window's program attribution: exactly the dispatches that ran
    # while the trace was open.
    assert line["programs"] == {"test_profiled": 2}


# ---------------------------------------------------------------------------
# Prometheus family grammar
# ---------------------------------------------------------------------------


def test_program_prometheus_families(private_ledger):
    guard = RetraceGuard("t", max_traces=1)
    fn = ledgered_jit(
        lambda x: (x @ x).sum(),
        guard,
        subsystem="gramm",
        program="prog",
    )
    for _ in range(3):
        fn(jnp.ones((8, 8)))
    private_ledger.record_watermark(4096.0)
    text = prometheus_exposition(private_ledger.snapshot())
    # Per-program facts fold into ONE labeled family per field.
    assert "# TYPE marl_program_flops gauge" in text
    assert 'marl_program_flops{program="gramm_prog"} ' in text
    # Dispatch percentiles fold into a summary family with BOTH labels.
    assert "# TYPE marl_program_dispatch_seconds summary" in text
    assert (
        'marl_program_dispatch_seconds{program="gramm_prog",'
        'quantile="0.5"} ' in text
    )
    # Counters keep counter typing under the fold.
    assert "# TYPE marl_program_dispatches_total counter" in text
    assert (
        'marl_program_dispatches_total{program="gramm_prog"} 2.0'
        in text
    )
    # Aggregates ride beside them.
    assert "marl_ledger_programs_total 1.0" in text
    assert "marl_device_memory_watermark_bytes 4096.0" in text
    # Every line parses under the exposition grammar.
    import re

    line_re = re.compile(
        r"^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
        r"(?:counter|gauge|summary|histogram))$"
        r"|^[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})? "
        r"(?:[-+]?(?:\d+\.?\d*(?:e[-+]?\d+)?|Inf|NaN))$",
        re.IGNORECASE,
    )
    for line in text.strip().splitlines():
        assert line_re.match(line), f"unparseable line: {line!r}"


def test_merged_namespaces_carry_ledger(private_ledger):
    """TelemetryServer and the sentinel's default snapshot both see the
    ledger families without explicit wiring."""
    from marl_distributedformation_tpu.obs.metrics import (
        MetricsRegistry,
        TelemetryServer,
    )

    private_ledger.register(
        name="p", subsystem="s", facts={"flops": 42.0}
    )
    server = TelemetryServer(registry=MetricsRegistry())
    snap = server._snapshot()
    assert snap["program_s_p_flops"] == 42.0
    assert snap["ledger_programs_total"] == 1.0


# ---------------------------------------------------------------------------
# Census: report round-trip + diff gate
# ---------------------------------------------------------------------------


def _census_with(ledger):
    ledger.register(
        name="big", subsystem="train",
        facts={"flops": 1e9, "bytes_accessed": 1e8, "temp_bytes": 1e6,
               "argument_bytes": 5e5, "output_bytes": 1e5},
        timings={"compile_seconds": 3.0},
        analysis_source="executable",
    )
    ledger.register(
        name="small", subsystem="serve",
        facts={"flops": 1e6, "bytes_accessed": 1e5},
        timings={"compile_seconds": 0.2},
        analysis_source="lowered",
    )
    ledger.dispatch("train_big", 0.01)
    return ledger


def test_census_write_load_and_report_round_trip(
    private_ledger, tmp_path
):
    _census_with(private_ledger)
    path = private_ledger.write_census(tmp_path / "program_ledger.json")
    census = load_census(path)
    assert census["schema"] == CENSUS_SCHEMA
    assert census["totals"]["programs"] == 2
    assert census["totals"]["compile_seconds"] == pytest.approx(3.2)
    keys = [p["key"] for p in census["programs"]]
    assert keys == ["train_big", "serve_small"]
    big = census["programs"][0]
    assert big["dispatches_total"] == 1.0
    # The report renders and ranks it.
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import program_report
    finally:
        sys.path.pop(0)
    summary = program_report.summarize(census, top=5)
    assert summary["program_count"] == 2
    assert [
        p["key"] for p in summary["top"]["flops"]
    ] == ["train_big", "serve_small"]
    # dispatch_p95 ranking only includes programs that dispatched.
    assert [
        p["key"] for p in summary["top"]["dispatch_p95"]
    ] == ["train_big"]
    text = program_report.render_text(census, top=5)
    assert "train_big" in text and "top by compile" in text
    # A truncated file is a clean error, not a crash.
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    with pytest.raises(ValueError):
        load_census(bad)


def test_census_diff_gate(private_ledger, tmp_path):
    _census_with(private_ledger)
    committed = private_ledger.census()
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        from check_bench_record import census_diff
    finally:
        sys.path.pop(0)
    # Identical census: clean.
    assert census_diff(committed, committed) == []
    # Drifted flops past tolerance: named rejection.
    live = json.loads(json.dumps(committed))
    live["programs"][0]["flops"] = 2e9
    problems = census_diff(committed, live, tolerance=0.25)
    assert len(problems) == 1 and "flops drifted 100%" in problems[0]
    assert census_diff(committed, live, tolerance=1.5) == []
    # A vanished and a new program are both rejections.
    live = json.loads(json.dumps(committed))
    live["programs"][1]["dispatch_key"] = "serve_other"
    live["programs"][1]["key"] = "serve_other"
    problems = census_diff(committed, live)
    assert any("vanished" in p and "serve_small" in p for p in problems)
    assert any("new program" in p and "serve_other" in p for p in problems)
    # A replica's entry disappearing under a shared dispatch key is a
    # count change, not a vanished key — still a rejection.
    live = json.loads(json.dumps(committed))
    live["programs"].append(dict(live["programs"][0]))
    problems = census_diff(committed, live)
    assert any(
        "count changed (1 committed -> 2 live)" in p for p in problems
    )


def test_ledger_bench_validator(private_ledger):
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        from check_bench_record import check
    finally:
        sys.path.pop(0)
    base = {"platform": "tpu"}
    ok = {
        **base,
        "ledger_overhead_pct": 1.2,
        "ledger_program_count": 9,
        "ledger_compile_seconds_total": 31.5,
    }
    assert check(ok, [], []) == []
    assert check({**ok, "ledger_overhead_pct": 7.0}, [], [])
    assert check({**ok, "ledger_overhead_pct": float("nan")}, [], [])
    assert check({**ok, "ledger_program_count": 0}, [], [])
    assert check({**ok, "ledger_compile_seconds_total": -1.0}, [], [])
    skipped = {
        **base,
        "ledger_overhead_pct": "skipped",
        "ledger_program_count": "skipped",
        "ledger_compile_seconds_total": "skipped",
    }
    assert check(skipped, [], []) == []


# ---------------------------------------------------------------------------
# Sentinel: ledger watches trip the same machinery
# ---------------------------------------------------------------------------


def test_sentinel_trips_on_inflated_compile_seconds(
    private_ledger, tmp_path
):
    from marl_distributedformation_tpu.obs.metrics import MetricsRegistry
    from marl_distributedformation_tpu.obs.sentinel import (
        RegressionSentinel,
        ledger_watches,
    )
    from marl_distributedformation_tpu.obs.flightrec import FlightRecorder
    from marl_distributedformation_tpu.obs.tracer import Tracer

    tracer = Tracer(flightrec=FlightRecorder(tmp_path, last_n=64))
    sentinel = RegressionSentinel(
        ledger_watches(tolerance=0.5),
        record={
            "ledger_compile_seconds_max": 10.0,
            "device_memory_watermark_bytes": 1e6,
        },
        trip_after=2,
        audit_dir=tmp_path,
        registry=MetricsRegistry(),
        tracer=tracer,
    )
    healthy = {
        "ledger_compile_seconds_max": 11.0,
        "device_memory_bytes_in_use": 9e5,
    }
    assert sentinel.check(healthy) == []
    assert sentinel.check(healthy) == []
    inflated = {
        "ledger_compile_seconds_max": 40.0,  # > 10 * 1.5
        "device_memory_bytes_in_use": 9e5,
    }
    assert sentinel.check(inflated) == []  # streak 1 of 2
    trips = sentinel.check(inflated)
    assert len(trips) == 1
    assert trips[0]["gauge"] == "ledger_compile_seconds_max"
    # The trip wrote the audit line + flight record.
    audit = tmp_path / RegressionSentinel.AUDIT_NAME
    assert audit.exists()
    (line,) = [
        json.loads(ln) for ln in audit.read_text().splitlines() if ln
    ]
    assert line["event"] == "perf_regression"
    assert line["bench_field"] == "ledger_compile_seconds_max"
    dumps = list(tmp_path.glob("flightrec-perf_regression-*.json"))
    assert dumps, "the trip must dump a flight record"
    # A recovered sample re-arms the watch — the reason the gauge is
    # the per-program MAX, not a lifetime-cumulative total.
    assert sentinel.check(healthy) == []
    assert not sentinel._state["ledger_compile_seconds_max"].tripped


def test_sentinel_default_snapshot_merges_ledger(private_ledger):
    from marl_distributedformation_tpu.obs.metrics import MetricsRegistry
    from marl_distributedformation_tpu.obs.sentinel import (
        RegressionSentinel,
        ledger_watches,
    )
    from marl_distributedformation_tpu.obs.tracer import Tracer

    private_ledger.register(
        name="p", subsystem="s", timings={"compile_seconds": 2.0}
    )
    sentinel = RegressionSentinel(
        ledger_watches(),
        record={"ledger_compile_seconds_max": 2.0},
        registry=MetricsRegistry(),  # empty: the ledger is the source
        tracer=Tracer(enabled=False),
    )
    sentinel.check()  # no explicit snapshot: must merge the ledger
    summary = sentinel.summary()
    assert (
        "ledger_compile_seconds_max"
        not in summary["sentinel_never_observed"]
    )
