"""Adversarial scenario engine contracts (scenarios/adversary.py,
docs/adversarial.md).

The acceptance pins from the adversarial ISSUE:

- **severity 0 can never be a falsifier**: every registered scenario at
  severity 0 is BITWISE the clean cell through the vmapped population
  program (the search's comparison point), so its relative drop is
  exactly 0 — pinned over the whole registry;
- **search determinism** at a fixed seed: identical falsifier reports
  from independent searcher instances;
- **budget-1 compile receipt** across >= 3 generations x >= 2
  checkpoints: model params and scenario knobs are both traced, so the
  population program compiles exactly once, ever;
- ``ScenarioSpec.build`` / ``sample_scenario_batch`` fail fast on
  concrete negative / non-finite severities, naming the scenario;
- ``from_falsifiers`` registers stable ``adv:`` specs and builds a
  trainable stage; the Trainer applies a requested schedule at the next
  dispatch boundary with ZERO recompiles of the train program;
- END TO END: a gate with the adversarial rung rejects a weak
  checkpoint, the verdict carries the falsifier's concrete params
  (promotions.jsonl schema 3), and the supervisor feeds them back into
  the trainer's schedule — the train -> gate -> train loop closes.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

# Bitwise-stream tests must see the threefry-partitionable flag before
# any draws (tests/test_scenarios.py NB).
from marl_distributedformation_tpu import jax_compat  # noqa: F401
from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.models import MLPActorCritic
from marl_distributedformation_tpu.pipeline import (
    AlwaysLearningPipeline,
    GateConfig,
    PromotionLog,
    judge_falsifiers,
)
from marl_distributedformation_tpu.scenarios import (
    AdversaryConfig,
    AdversarySearch,
    ScenarioSchedule,
    ScenarioStage,
    from_falsifiers,
    get_scenario,
    registered_scenarios,
    sample_scenario_batch,
)
from marl_distributedformation_tpu.scenarios.adversary import (
    _stack_rows,
    make_population_runner,
)
from marl_distributedformation_tpu.train import TrainConfig, Trainer

ENV = EnvParams(num_agents=3, max_steps=20)


def _tiny_policy(seed=0):
    model = MLPActorCritic(act_dim=ENV.act_dim)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, ENV.obs_dim), jnp.float32)
    )
    return model, params


def _clean_schedule():
    return ScenarioSchedule(stages=(ScenarioStage(
        rollouts=1, scenarios=("clean",), severity=0.0, severity_start=0.0,
    ),))


def _tiny_trainer(log_dir, name="adv", scenario_schedule="clean", **cfg):
    if scenario_schedule == "clean":
        scenario_schedule = _clean_schedule()
    defaults = dict(
        num_formations=4, checkpoint=False, name=name,
        log_dir=str(log_dir),
    )
    defaults.update(cfg)
    return Trainer(
        ENV,
        ppo=PPOConfig(n_steps=5, n_epochs=1, batch_size=32),
        config=TrainConfig(**defaults),
        scenario_schedule=scenario_schedule,
    )


# ---------------------------------------------------------------------------
# The population program + the search
# ---------------------------------------------------------------------------


def test_severity_zero_is_never_a_falsifier_any_scenario():
    """Bitwise pin over the WHOLE registry: a severity-0 row of any
    scenario reproduces the clean row exactly through the vmapped
    population program, so its relative drop vs clean is identically 0
    — severity 0 cannot falsify, by construction not by tolerance."""
    model, params = _tiny_policy()
    run, guard = make_population_runner(model, ENV, num_formations=3)
    names = registered_scenarios()
    rows = [(get_scenario("clean"), 0.0)] + [
        (get_scenario(name), 0.0) for name in names
    ]
    out = run(jax.random.PRNGKey(0), params, _stack_rows(rows))
    assert guard.count == 1
    host = jax.device_get(out)
    for metric, values in host.items():
        values = np.asarray(values)
        for i, name in enumerate(names):
            assert values[i + 1].tobytes() == values[0].tobytes(), (
                f"scenario {name} at severity 0 drifted the clean "
                f"{metric} — severity 0 would become a spurious falsifier"
            )


def test_search_finds_falsifier_with_positive_severity():
    model, params = _tiny_policy()
    search = AdversarySearch(model, ENV, AdversaryConfig(
        scenarios=("wind",), grid=3, generations=3, num_formations=4,
        drop_tolerance=0.02, resolution=0.001,
    ))
    report = search.search(params, origin="init")
    assert report["falsifiers"], "an untrained policy must break under wind"
    falsifier = report["falsifiers"][0]
    assert falsifier["scenario"] == "wind"
    assert 0.0 < falsifier["severity"] <= search.config.max_severity
    assert falsifier["drop"] > search.config.drop_tolerance
    # The falsifier carries the concrete knobs (the portable payload
    # from_falsifiers and the gate verdicts consume).
    assert falsifier["params"]["wind"][0] > 0.0
    assert report["eval_compiles"] == 1


def test_search_is_deterministic_at_fixed_seed():
    model, params = _tiny_policy()
    cfg = AdversaryConfig(
        scenarios=("wind", "sensor_noise"), grid=3, generations=3,
        num_formations=4, drop_tolerance=0.02,
    )
    reports = [
        AdversarySearch(model, ENV, cfg).search(params, origin="x")
        for _ in range(2)
    ]
    for rep in reports:
        rep.pop("search_seconds")
    assert json.dumps(reports[0], sort_keys=True) == json.dumps(
        reports[1], sort_keys=True
    )


def test_search_compiles_once_across_generations_and_checkpoints():
    """The budget-1 receipt the gate and the bench record: >= 3
    generations x >= 2 same-architecture checkpoints through ONE
    compiled population program (resolution 0 keeps refining, so the
    generation budget is fully spent)."""
    model, params_a = _tiny_policy(seed=0)
    _, params_b = _tiny_policy(seed=1)
    search = AdversarySearch(model, ENV, AdversaryConfig(
        scenarios=("wind",), grid=3, generations=3, num_formations=4,
        drop_tolerance=0.02, resolution=0.0,
    ))
    rep_a = search.search(params_a, origin="ckpt_a")
    rep_b = search.search(params_b, origin="ckpt_b")
    assert rep_a["generations"] >= 3 and rep_b["generations"] >= 3
    assert search.compile_count == 1
    assert search.candidates_per_sec() > 0.0
    # A different architecture is a clean error, not a surprise retrace.
    wide_model = MLPActorCritic(act_dim=ENV.act_dim, hidden=(8,))
    wide = wide_model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, ENV.obs_dim), jnp.float32)
    )
    with pytest.raises(ValueError, match="different parameter"):
        search.search(wide, origin="ckpt_wide")


# ---------------------------------------------------------------------------
# Severity validation (fail fast, naming the scenario)
# ---------------------------------------------------------------------------


def test_build_rejects_negative_and_nonfinite_severity():
    spec = get_scenario("wind")
    with pytest.raises(ValueError, match="'wind'.*>= 0"):
        spec.build(-0.5)
    with pytest.raises(ValueError, match="'wind'.*finite"):
        spec.build(float("nan"))
    with pytest.raises(ValueError, match="'wind'.*finite"):
        spec.build(float("inf"))
    # The traced path is untouched: a jitted builder traces and runs.
    jitted = jax.jit(spec.build)
    params = jitted(jnp.float32(0.5))
    assert float(params.wind[0]) == pytest.approx(2.0)


def test_sample_scenario_batch_rejects_bad_severity():
    specs = (get_scenario("wind"), get_scenario("sensor_noise"))
    key = jax.random.PRNGKey(0)
    probs = jnp.asarray([0.5, 0.5], jnp.float32)
    with pytest.raises(ValueError, match="wind.*sensor_noise"):
        sample_scenario_batch(key, -1.0, probs, specs, 4)
    with pytest.raises(ValueError, match="finite"):
        sample_scenario_batch(key, float("nan"), probs, specs, 4)


# ---------------------------------------------------------------------------
# from_falsifiers -> trainer (the curriculum half of the loop)
# ---------------------------------------------------------------------------


def test_from_falsifiers_registers_stable_specs_and_stage():
    schedule = from_falsifiers(
        [{"scenario": "wind", "severity": 0.8},
         {"scenario": "sensor_noise", "severity": 0.4}],
        rollouts=12,
    )
    assert schedule.names == ("adv:wind", "adv:sensor_noise", "clean")
    stage = schedule.stages[0]
    assert stage.rollouts == 12 and stage.severity == 1.0
    # Derived magnitudes = base x falsifier severity, trained at 1.0.
    adv = get_scenario("adv:wind")
    assert adv.wind_x == pytest.approx(get_scenario("wind").wind_x * 0.8)
    # Re-feeding the same family overwrites IN PLACE: the name union
    # (and with it the trainer's sampler axis) never grows.
    again = from_falsifiers(
        [{"scenario": "wind", "severity": 0.3}], rollouts=5,
    )
    assert again.names == ("adv:wind", "clean")
    assert get_scenario("adv:wind").wind_x == pytest.approx(
        get_scenario("wind").wind_x * 0.3
    )
    with pytest.raises(ValueError, match="positive"):
        from_falsifiers([{"scenario": "wind", "severity": 0.0}])
    with pytest.raises(ValueError, match="positive"):
        from_falsifiers([{"scenario": "wind", "severity": float("nan")}])
    with pytest.raises(ValueError, match="unknown scenario"):
        from_falsifiers([{"scenario": "no_such", "severity": 0.5}])


def test_trainer_applies_requested_schedule_with_zero_recompiles(tmp_path):
    """The zero-recompile contract of the auto-curriculum seam: swapping
    the schedule mid-run (changed spec union included) rebuilds only the
    tiny sampler — the compiled train step is untouched (budget-1
    RetraceGuard across the swap)."""
    trainer = _tiny_trainer(tmp_path, scenario_schedule=_clean_schedule())
    trainer.run_iteration()
    trainer.run_iteration()
    assert trainer.retrace_guard.count == 1
    trainer.request_scenario_schedule(from_falsifiers(
        [{"scenario": "wind", "severity": 0.7}], rollouts=4,
    ))
    # Not applied yet — the training thread owns schedule state and
    # applies at its next dispatch boundary.
    assert trainer._scenario_schedule.names == ("clean",)
    trainer.run_iteration()
    assert trainer._scenario_schedule.names == ("adv:wind", "clean")
    assert trainer.scenario_severity == 1.0
    trainer.run_iteration()
    assert trainer.retrace_guard.count == 1, (
        "a curriculum swap must never recompile the train program"
    )


def test_schedule_swap_never_replays_sampling_draws(tmp_path):
    """A curriculum swap resets the SCHEDULE position but not the
    sampling-key stream: the draw counter keeps climbing, so the first
    post-swap scenario mix cannot bitwise-replay the run's first draw
    (the key-replay bug a plain rollout-counter reset would cause)."""
    schedule = ScenarioSchedule(stages=(ScenarioStage(
        rollouts=1, scenarios=("wind", "sensor_noise"),
        severity=0.5, severity_start=0.5,
    ),))
    trainer = _tiny_trainer(
        tmp_path, name="adv_draws", scenario_schedule=schedule,
        num_formations=16,
    )
    first_draw = jax.device_get(trainer.scenario_params)
    trainer.run_iteration()
    trainer.run_iteration()
    # Same schedule VALUE re-installed: severity and probs match the
    # first draw exactly, so only the sampling key can differ.
    trainer.update_scenario_schedule(ScenarioSchedule(stages=(
        ScenarioStage(rollouts=1, scenarios=("wind", "sensor_noise"),
                      severity=0.5, severity_start=0.5),
    )))
    assert trainer._scenario_rollouts == 0
    assert trainer._scenario_draws == 2, "draw counter must never reset"
    post_swap = jax.device_get(trainer.scenario_params)
    leaves_a = jax.tree_util.tree_leaves(first_draw)
    leaves_b = jax.tree_util.tree_leaves(post_swap)
    assert any(
        a.tobytes() != b.tobytes() for a, b in zip(leaves_a, leaves_b)
    ), "post-swap mix replayed the run's first sampling draw"


def test_fused_trainer_applies_schedule_between_chunks(tmp_path):
    trainer = _tiny_trainer(
        tmp_path, name="adv_fused", fused_chunk=2,
        scenario_schedule=_clean_schedule(),
    )
    jax.block_until_ready(trainer.run_chunk()["reward"])
    trainer.request_scenario_schedule(from_falsifiers(
        [{"scenario": "sensor_noise", "severity": 0.5}], rollouts=4,
    ))
    jax.block_until_ready(trainer.run_chunk()["reward"])
    assert trainer._scenario_schedule.names == ("adv:sensor_noise", "clean")
    assert trainer.retrace_guard.count == 1


def test_update_schedule_without_scenario_seam_fails_fast(tmp_path):
    trainer = _tiny_trainer(
        tmp_path, name="adv_noseam", scenario_schedule=None,
    )
    schedule = from_falsifiers(
        [{"scenario": "wind", "severity": 0.5}], rollouts=2,
    )
    with pytest.raises(ValueError, match="scenarios=\\['clean'\\]"):
        trainer.update_scenario_schedule(schedule)
    with pytest.raises(ValueError, match="scenarios=\\['clean'\\]"):
        trainer.request_scenario_schedule(schedule)


# ---------------------------------------------------------------------------
# The gate rung + the closed loop
# ---------------------------------------------------------------------------


def test_judge_falsifiers_rejects_only_below_floor():
    falsifiers = [
        {"scenario": "wind", "severity": 0.3, "drop": 0.5},
        {"scenario": "storm", "severity": 1.2, "drop": 0.4},
    ]
    reasons = judge_falsifiers(falsifiers, 0.5, "episode_return_per_agent")
    assert len(reasons) == 1 and "wind@0.3" in reasons[0]
    assert judge_falsifiers(falsifiers, 0.1, "m") == []
    # A falsifier with a broken severity is a rejection, not a pass.
    assert judge_falsifiers(
        [{"scenario": "wind", "severity": float("nan"), "drop": 1.0}],
        0.5, "m",
    )


def test_gate_rejection_feeds_trainer_schedule_end_to_end(tmp_path):
    """THE loop: trainer checkpoint -> adversarial gate rejection whose
    verdict carries the falsifier params (promotions.jsonl schema 3) ->
    supervisor feeds them to the trainer -> the next dispatch trains on
    the falsifier stage — with budget-1 receipts for the gate's search
    across candidates AND the train program across the swap."""
    log_dir = tmp_path / "run"
    trainer = _tiny_trainer(
        log_dir, name="adv_e2e", scenario_schedule=_clean_schedule(),
        checkpoint=True, save_freq=5, total_timesteps=5 * 4 * 3,
    )
    trainer.run_iteration()
    trainer.save()
    pipeline = AlwaysLearningPipeline(
        log_dir,
        ENV,
        gate_config=GateConfig(
            scenarios=("wind",), severities=(1.0,), eval_formations=4,
            adversarial=True, adversarial_min_severity=10.0,
            adversarial_grid=3, adversarial_generations=2,
            adversarial_formations=4, adversarial_drop_tolerance=0.02,
        ),
        poll_interval_s=0.01,
        feedback_rollouts=9,
    )
    pipeline.attach_trainer(trainer)
    assert pipeline.poll_once() == 1
    assert len(pipeline.rejections) == 1
    verdict = pipeline.rejections[0]
    assert verdict.falsifiers, "the rejection must carry its falsifiers"
    assert any("adversarial falsifier" in r for r in verdict.reasons)
    assert verdict.adversary_compiles == 1
    assert pipeline.curriculum_updates == 1

    records = PromotionLog.read(log_dir / "promotions.jsonl")
    events = [r["event"] for r in records]
    assert events == ["rejected", "curriculum_updated"]
    rejected = records[0]
    from marl_distributedformation_tpu.pipeline.promote import (
        PROMOTIONS_SCHEMA,
    )

    assert rejected["schema"] == PROMOTIONS_SCHEMA
    assert rejected["falsifiers"][0]["scenario"] == "wind"
    assert rejected["falsifiers"][0]["params"]["wind"][0] > 0.0
    updated = records[1]
    assert updated["feedback_rollouts"] == 9
    assert "adv:wind" in updated["scenarios"]

    # The training thread picks the stage up at its next dispatch, with
    # zero recompiles of the train program.
    trainer.run_iteration()
    assert "adv:wind" in trainer._scenario_schedule.names
    assert trainer.retrace_guard.count == 1

    # A second candidate reuses BOTH compiled gate programs (matrix +
    # adversary): budget-1 across the candidate series.
    trainer.run_iteration()
    trainer.save()
    pipeline.poll_once()
    assert len(pipeline.rejections) == 2
    assert pipeline.gate.adversary.compile_count == 1
    assert pipeline.gate.program.compile_count == 1
    # summary() surfaces the feedback loop for the CLI's JSON line.
    assert pipeline.summary()["curriculum_updates"] == 2
