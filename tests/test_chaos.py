"""Chaos plane contract (tier-1): deterministic fault injection, the
crash-consistent checkpoint format, graceful writer degradation,
self-healing lane supervision, the gate-eval deadline, the invariant
checkers, and ONE seeded micro-campaign through trainer -> gate ->
fleet (scripts/chaos_storm.py) with zero invariant violations.

The acceptance pins from the chaos ISSUE:

- a disabled plane is a no-op (and the shipped default);
- a FaultSchedule is a pure function of its seed (bit-identical
  replay) and rejects malformed specs;
- the checksum footer catches bit-flips/truncation, corrupt files are
  QUARANTINED (renamed aside, audit-logged, invisible to discovery)
  instead of wedging resume, and legacy footer-less checkpoints stay
  readable;
- a crash between tmp-write and rename leaves nothing discoverable;
- ENOSPC/crash under the AsyncCheckpointWriter degrades to
  skip-with-audit — never a dead training run;
- the LaneWatchdog restarts a wedged AND a dead pipeline lane;
- a wedged candidate yields a ``gate_timeout`` verdict;
- invariant trips dump ``chaos_violation`` flight records carrying the
  armed fault schedule.
"""

import json
import time

import numpy as np
import pytest

from marl_distributedformation_tpu.chaos import (
    FAULT_KINDS,
    FaultPlane,
    FaultSchedule,
    FaultSpec,
    InjectedFault,
    LaneWatchdog,
    SimulatedCrash,
    Violation,
    check_audit_log,
    check_budget_one,
    check_checkpoint_dir,
    check_no_request_lost,
    check_step_monotonic,
    get_fault_plane,
    report_violations,
    set_fault_plane,
)
from marl_distributedformation_tpu.utils.checkpoint import (
    AsyncCheckpointWriter,
    CorruptCheckpointError,
    _write_atomic,
    checkpoint_path,
    latest_checkpoint,
    msgpack_restore_file,
    restore_checkpoint,
    restore_latest_partial,
)


@pytest.fixture
def plane():
    """A test-private FaultPlane installed as the process-global one;
    the shipped default (disabled) is restored afterwards."""
    fresh = FaultPlane(enabled=True)
    previous = set_fault_plane(fresh)
    yield fresh
    set_fault_plane(previous)


@pytest.fixture
def private_registry():
    from marl_distributedformation_tpu.obs import (
        MetricsRegistry,
        set_registry,
    )

    registry = MetricsRegistry()
    previous = set_registry(registry)
    yield registry
    set_registry(previous)


@pytest.fixture
def private_tracer(tmp_path):
    from marl_distributedformation_tpu.obs import (
        FlightRecorder,
        Tracer,
        set_tracer,
    )

    tracer = Tracer(
        ring_size=1024,
        flightrec=FlightRecorder(tmp_path / "flightrec", last_n=128),
    )
    previous = set_tracer(tracer)
    yield tracer
    set_tracer(previous)


def _target():
    return {
        "params": np.arange(64, dtype=np.float32).reshape(8, 8),
        "num_timesteps": 40,
    }


# ---------------------------------------------------------------------------
# FaultPlane / FaultSchedule
# ---------------------------------------------------------------------------


def test_disabled_plane_is_a_noop():
    plane = FaultPlane(enabled=False)
    plane.arm(FaultSchedule([FaultSpec("stream.poll", "raise", 1)]))
    for _ in range(5):
        plane.hit("stream.poll")  # armed but disabled: nothing fires
    assert plane.fired == []
    assert plane.pending() == 1
    # The shipped process-global default is disabled.
    assert get_fault_plane().enabled is False


def test_schedule_deterministic_from_seed_and_kind_coverage():
    a = FaultSchedule.from_seed(42, faults=25)
    b = FaultSchedule.from_seed(42, faults=25)
    assert json.dumps(a.record()) == json.dumps(b.record())
    assert len(a) == 25
    # The coverage pass guarantees every kind appears.
    assert {s.kind for s in a.specs} == set(FAULT_KINDS)
    # A different seed is a different schedule.
    c = FaultSchedule.from_seed(43, faults=25)
    assert json.dumps(a.record()) != json.dumps(c.record())


def test_schedule_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSchedule([FaultSpec("stream.poll", "meteor", 1)])
    with pytest.raises(ValueError, match="cannot express"):
        # checkpoint.write is IO-shaped: generic raise not armable.
        FaultSchedule([FaultSpec("checkpoint.write", "raise", 1)])
    with pytest.raises(ValueError, match="duplicate fault cell"):
        FaultSchedule([
            FaultSpec("stream.poll", "raise", 1),
            FaultSpec("stream.poll", "delay", 1),
        ])


def test_fault_fires_at_exact_hit(plane):
    plane.arm(FaultSchedule([FaultSpec("stream.poll", "raise", 3)]))
    plane.hit("stream.poll")
    plane.hit("stream.poll")
    with pytest.raises(InjectedFault):
        plane.hit("stream.poll")
    plane.hit("stream.poll")  # one-shot: consumed
    assert [f["at_hit"] for f in plane.fired_record()] == [3]


# ---------------------------------------------------------------------------
# Crash-consistent checkpoint format (hardening a)
# ---------------------------------------------------------------------------


def test_footer_roundtrip_and_legacy_files_readable(tmp_path):
    from flax import serialization

    path = checkpoint_path(tmp_path, 40)
    _write_atomic(path, _target())
    restored = restore_checkpoint(path, _target())
    np.testing.assert_array_equal(restored["params"], _target()["params"])
    # A legacy (footer-less) file written before the chaos plane still
    # reads — the format is backward-compatible.
    legacy = checkpoint_path(tmp_path / "legacy", 40)
    legacy.parent.mkdir()
    legacy.write_bytes(serialization.to_bytes(_target()))
    restored = restore_checkpoint(legacy, _target())
    assert int(restored["num_timesteps"]) == 40


def test_bitflip_is_quarantined_not_served(
    tmp_path, private_registry, private_tracer
):
    from marl_distributedformation_tpu.chaos.plane import _corrupt_file

    path = checkpoint_path(tmp_path, 40)
    _write_atomic(path, _target())
    _corrupt_file(str(path), "bitflip")
    with pytest.raises(CorruptCheckpointError):
        msgpack_restore_file(path)
    # Quarantined: renamed aside, invisible to discovery, audit-logged.
    assert not path.exists()
    assert path.with_name(path.name + ".quarantined").exists()
    assert latest_checkpoint(tmp_path) is None
    audit = json.loads(
        (tmp_path / "quarantine.jsonl").read_text().splitlines()[0]
    )
    assert audit["file"] == path.name and "checksum" in audit["reason"]
    assert (
        private_registry.snapshot()["checkpoint_quarantined_total"] == 1.0
    )
    # The directory now passes the crash-consistency invariant.
    assert check_checkpoint_dir(tmp_path) == []


def test_truncation_walkback_resumes_from_newest_valid(
    tmp_path, private_registry, private_tracer
):
    """A truncated NEWEST checkpoint costs one checkpoint of progress,
    never a wedged resume: restore_latest_partial quarantines it and
    walks back to the older valid file."""
    good = checkpoint_path(tmp_path, 40)
    _write_atomic(good, _target())
    bad = checkpoint_path(tmp_path, 80)
    _write_atomic(bad, {**_target(), "num_timesteps": 80})
    with open(bad, "r+b") as f:
        f.truncate(bad.stat().st_size // 2)
    found = restore_latest_partial(tmp_path, _target())
    assert found is not None
    path, restored = found
    assert path == good
    assert int(restored["num_timesteps"]) == 40
    assert not bad.exists()
    assert check_checkpoint_dir(tmp_path) == []


def test_crash_mid_rename_leaves_nothing_discoverable(plane, tmp_path):
    plane.arm(
        FaultSchedule([FaultSpec("checkpoint.pre_rename", "crash", 1)])
    )
    path = checkpoint_path(tmp_path, 40)
    with pytest.raises(SimulatedCrash):
        _write_atomic(path, _target())
    # The torn write is a dot-prefixed tmp only: invisible to discovery,
    # clean under the crash-consistency invariant.
    assert not path.exists()
    assert (tmp_path / f".{path.name}.tmp").exists()
    assert latest_checkpoint(tmp_path) is None
    assert check_checkpoint_dir(tmp_path) == []


# ---------------------------------------------------------------------------
# AsyncCheckpointWriter degradation (hardening b)
# ---------------------------------------------------------------------------


def test_writer_transient_enospc_retries_and_lands(plane, tmp_path):
    plane.arm(
        FaultSchedule([FaultSpec("checkpoint.write", "enospc", 1)])
    )
    writer = AsyncCheckpointWriter(io_retries=3, io_backoff_s=0.001)
    path = writer.submit(checkpoint_path(tmp_path, 40), _target())
    writer.close()  # would raise on a surfaced failure
    assert path.exists()  # the retry landed the write
    assert writer.writes_skipped == 0
    restored = restore_checkpoint(path, _target())
    assert int(restored["num_timesteps"]) == 40


def test_writer_persistent_enospc_skips_with_audit(
    plane, tmp_path, private_registry, private_tracer
):
    plane.arm(
        FaultSchedule([
            FaultSpec("checkpoint.write", "enospc", h) for h in (1, 2, 3)
        ])
    )
    writer = AsyncCheckpointWriter(io_retries=2, io_backoff_s=0.001)
    path = writer.submit(checkpoint_path(tmp_path, 40), _target())
    writer.wait()  # must NOT raise: degraded, not dead
    assert not path.exists()
    assert writer.writes_skipped == 1
    snap = private_registry.snapshot()
    assert snap["checkpoint_writes_skipped_total"] == 1.0
    dumps = [
        p.name for p in private_tracer.flightrec.dumps()
    ]
    assert any("checkpoint_write_skipped" in n for n in dumps)
    # The writer is still healthy: the NEXT write succeeds.
    path2 = writer.submit(checkpoint_path(tmp_path, 80), _target())
    writer.close()
    assert path2.exists()


def test_writer_injected_crash_skips_with_audit(
    plane, tmp_path, private_registry, private_tracer
):
    plane.arm(
        FaultSchedule([FaultSpec("checkpoint.pre_rename", "crash", 1)])
    )
    writer = AsyncCheckpointWriter(io_retries=2, io_backoff_s=0.001)
    path = writer.submit(checkpoint_path(tmp_path, 40), _target())
    writer.close()  # a crashed write is SKIPPED, never surfaced
    assert not path.exists()
    assert writer.writes_skipped == 1
    assert latest_checkpoint(tmp_path) is None  # tmp stays invisible
    # Non-IO failures still surface — program errors are not weather.
    writer2 = AsyncCheckpointWriter()
    writer2.submit_write(lambda: (_ for _ in ()).throw(TypeError("bug")))
    with pytest.raises(RuntimeError, match="async checkpoint write"):
        writer2.close()


# ---------------------------------------------------------------------------
# Watchdog (hardening c)
# ---------------------------------------------------------------------------


def test_watchdog_restarts_wedged_then_dead_pipeline_lane(
    plane, tmp_path, private_registry, private_tracer
):
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.pipeline import (
        AlwaysLearningPipeline,
    )

    pipeline = AlwaysLearningPipeline(
        tmp_path, EnvParams(num_agents=3, max_steps=20),
        poll_interval_s=0.01,
    )
    plane.arm(
        FaultSchedule([
            FaultSpec("pipeline.poll", "wedge", 2, seconds=1.5),
            FaultSpec("pipeline.poll", "crash", 30),
        ])
    )
    watchdog = LaneWatchdog(
        wedge_timeout_s=0.3, backoff_base_s=0.02, poll_interval_s=0.03
    )
    watchdog.watch_pipeline(pipeline)
    watchdog.start()
    pipeline.run(interval_s=0.01)
    deadline = time.monotonic() + 20.0
    while watchdog.restarts_total() < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    plane.enabled = False
    try:
        assert watchdog.restarts_total() >= 2, watchdog.restart_log
        reasons = [e["reason"] for e in watchdog.restart_log]
        assert any("stale" in r for r in reasons)  # the wedge
        assert any("dead" in r for r in reasons)  # the crash
        # The lane is ALIVE again after both injuries.
        assert pipeline.loop_alive()
        snap = private_registry.snapshot()
        assert snap["pipeline_restarts_total"] >= 2.0
        # Every self-heal left a postmortem flight record.
        assert any(
            "lane_restart" in p.name
            for p in private_tracer.flightrec.dumps()
        )
    finally:
        watchdog.stop()
        pipeline.stop()


# ---------------------------------------------------------------------------
# Gate-eval deadline (hardening d)
# ---------------------------------------------------------------------------


def test_gate_timeout_verdict(plane, tmp_path, private_registry):
    import dataclasses

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.pipeline import (
        GateConfig,
        PromotionGate,
    )
    from marl_distributedformation_tpu.train import TrainConfig, Trainer
    from marl_distributedformation_tpu.utils.checkpoint import (
        checkpoint_step,
    )

    env = EnvParams(num_agents=3, max_steps=20)
    trainer = Trainer(
        env,
        ppo=PPOConfig(n_steps=5, n_epochs=2, batch_size=32),
        config=TrainConfig(
            num_formations=4, total_timesteps=2 * 4 * 3 * 5,
            save_freq=5, name="chaos_gate", log_dir=str(tmp_path),
        ),
    )
    trainer.train()
    ckpt = latest_checkpoint(tmp_path)
    assert ckpt is not None
    cfg = GateConfig(
        scenarios=("wind",), severities=(1.0,), eval_formations=4,
        clean_tolerance=10.0, rung_tolerance=10.0,
    )
    gate = PromotionGate(env, cfg)
    plane.enabled = False
    warm = gate.evaluate(ckpt)  # compile outside the deadline
    assert warm.passed and not warm.timed_out
    gate.config = dataclasses.replace(cfg, gate_timeout_s=0.3)
    plane.enabled = True
    plane.arm(
        FaultSchedule([FaultSpec("gate.eval", "wedge", 1, seconds=1.5)])
    )
    verdict = gate.evaluate(ckpt)
    assert not verdict.passed and verdict.timed_out
    assert verdict.reasons[0].startswith("gate_timeout:")
    assert verdict.record()["gate_timeout"] is True
    assert verdict.step == checkpoint_step(ckpt)
    snap = private_registry.snapshot()
    assert snap["pipeline_gate_timeouts_total"] == 1.0
    # The stream moves on: the next candidate evaluates normally (the
    # abandoned wedged thread finishes harmlessly in the background,
    # and the compiled program stayed budget-1).
    time.sleep(1.6)
    ok = gate.evaluate(ckpt)
    assert ok.passed and not ok.timed_out
    assert gate.program.compile_count == 1


# ---------------------------------------------------------------------------
# Invariant checkers + the chaos_violation alarm
# ---------------------------------------------------------------------------


def test_invariant_checkers_unit(tmp_path):
    # Step monotonicity: backward is a violation unless an audited
    # rollback explains the exact step landed on.
    assert check_step_monotonic([(0, 10), (1, 20), (2, 20)]) == []
    trips = check_step_monotonic([(0, 10), (1, 20), (2, 10)])
    assert len(trips) == 1 and trips[0].invariant == "step_monotonic"
    assert check_step_monotonic(
        [(0, 10), (1, 20), (2, 10)], rollback_to_steps=[10]
    ) == []
    # Lost requests: only HUNG futures trip (typed errors resolved).
    assert check_no_request_lost(
        [{"ok": True, "hung": False}, {"ok": False, "hung": False}]
    ) == []
    assert check_no_request_lost([{"ok": False, "hung": True}])
    # Budget-1 receipts.
    assert check_budget_one({"gate": 1, "rung8": 0}) == []
    assert check_budget_one({"gate": 2})[0].invariant == "budget_one"
    # Audit log: ascending promotions, rollback to a promoted step.
    log = tmp_path / "promotions.jsonl"
    lines = [
        {"schema": 3, "event": "promoted", "time": 1.0, "step": 10},
        {"schema": 3, "event": "rejected", "time": 2.0, "step": 15},
        {"schema": 3, "event": "promoted", "time": 3.0, "step": 20},
        {"schema": 3, "event": "rolled_back", "time": 4.0,
         "from_step": 20, "to_step": 10},
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in lines))
    assert check_audit_log(log) == []
    lines.append({"schema": 3, "event": "promoted", "time": 5.0, "step": 5})
    lines.append({"schema": 3, "event": "rolled_back", "time": 6.0,
                  "from_step": 5, "to_step": 7})
    log.write_text("".join(json.dumps(r) + "\n" for r in lines))
    trips = check_audit_log(log)
    assert {t.invariant for t in trips} == {"audit_log"}
    assert len(trips) == 2  # non-ascending promote + rollback to ghost
    # Checkpoint dir: a corrupt DISCOVERABLE file trips; a quarantined
    # one does not (covered in the quarantine tests above).
    d = tmp_path / "ckpts"
    d.mkdir()
    _write_atomic(checkpoint_path(d, 40), _target())
    assert check_checkpoint_dir(d) == []
    bad = checkpoint_path(d, 80)
    _write_atomic(bad, _target())
    with open(bad, "r+b") as f:
        f.seek(10)
        f.write(b"\x00\x01\x02")
    trips = check_checkpoint_dir(d)
    assert len(trips) == 1
    assert trips[0].invariant == "checkpoint_crash_consistency"


def test_chaos_violation_dumps_flight_record_with_schedule(
    plane, private_tracer, private_registry
):
    plane.arm(FaultSchedule([FaultSpec("stream.poll", "raise", 9)]))
    records = report_violations(
        [Violation("step_monotonic", "went backward 20 -> 10")],
        plane,
    )
    assert len(records) == 1
    dumps = [
        p
        for p in private_tracer.flightrec.dumps()
        if "chaos_violation" in p.name
    ]
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    ctx = payload["context"]
    assert ctx["invariant"] == "step_monotonic"
    # The armed fault schedule rides the dump as STRUCTURED context —
    # the campaign is diagnosable from its artifacts alone.
    assert ctx["fault_schedule_armed"] == [
        {"point": "stream.poll", "kind": "raise", "at_hit": 9,
         "seconds": 0.0}
    ]
    snap = private_registry.snapshot()
    assert snap["chaos_invariant_violations_total"] == 1.0


# ---------------------------------------------------------------------------
# The storm: one seeded micro-campaign, end to end
# ---------------------------------------------------------------------------


def test_chaos_storm_campaign_zero_violations(tmp_path):
    """ONE full campaign at tiny scale: >= 25 faults spanning every
    kind through trainer -> gate -> fleet, zero invariant violations,
    finite MTTR, ~0 disabled-plane overhead — and the deterministic
    report section equals the pure-function schedule for the seed
    (what ``--print-schedule`` emits), pinning bit-identical replay."""
    import pathlib
    import sys

    scripts = pathlib.Path(__file__).resolve().parent.parent / "scripts"
    sys.path.insert(0, str(scripts))
    try:
        from chaos_storm import build_schedule, run_campaign
    finally:
        sys.path.pop(0)

    plane = get_fault_plane()
    try:
        report = run_campaign(
            seed=7,
            faults=25,
            workdir=str(tmp_path),
            budget_s=150.0,
            wedge_s=1.2,
            gate_timeout_s=0.6,
        )
    finally:
        plane.enabled = False
        plane.reset()
    assert report["chaos_invariant_violations"] == 0, report.get(
        "chaos_violations"
    )
    assert report["chaos_faults_fired"] == 25
    assert report["chaos_faults_unfired"] == 0
    assert report["resume_ok"]
    assert 0.0 < report["chaos_mttr_s"] < 60.0
    assert report["fault_plane_overhead_pct"] < 5.0
    assert report["probes_ok"] > 0
    # Replay determinism: the report's deterministic section is exactly
    # the seed's pure-function schedule.
    expected = build_schedule(7, 25, wedge_s=1.2)
    assert report["deterministic"] == {
        "chaos_seed": 7,
        "chaos_faults_armed": 25,
        "schedule": expected.record(),
    }
    kinds = {f["kind"] for f in expected.record()}
    assert {"crash", "wedge", "enospc", "delay"} <= kinds
    assert kinds & {"truncate", "bitflip"}  # corrupt coverage


def test_chaos_storm_train_campaign_zero_violations(tmp_path):
    """The --train storm (ISSUE 15): a live fused run with the health
    word + recovery ladder armed absorbs NaN carry bombs / grad bombs /
    snapshot corruption plus the PR-12 write-path weather — every fault
    fires, zero invariant violations (crash consistency, NO non-finite
    checkpoint visible, finite finish without halting, bounded MTTR,
    budget-1 receipts), and the deterministic report section equals the
    seed's pure-function schedule (the one-JSON-line contract)."""
    import pathlib
    import sys

    scripts = pathlib.Path(__file__).resolve().parent.parent / "scripts"
    sys.path.insert(0, str(scripts))
    try:
        from chaos_storm import (
            TRAIN_LANE_POINTS,
            TRAIN_POINTS,
            build_schedule,
            run_train_campaign,
        )
    finally:
        sys.path.pop(0)

    plane = get_fault_plane()
    try:
        report = run_train_campaign(
            seed=2, faults=10, workdir=str(tmp_path)
        )
    finally:
        plane.enabled = False
        plane.reset()
    assert report["chaos_invariant_violations"] == 0, report.get(
        "chaos_violations"
    )
    assert report["chaos_faults_fired"] == 10
    assert report["chaos_faults_unfired"] == 0
    assert not report["train_halted"]
    assert report["train_recoveries"] >= 1  # seed 2 arms poison raises
    assert 0.0 < report["recovery_mttr_s"] < 60.0
    expected = build_schedule(
        2, 10, point_names=TRAIN_LANE_POINTS + TRAIN_POINTS
    )
    assert report["deterministic"] == {
        "chaos_seed": 2,
        "chaos_faults_armed": 10,
        "schedule": expected.record(),
    }
