"""Scenario engine contracts (scenarios/, docs/scenarios.md).

The two load-bearing invariants:

1. **Severity-0 identity, bitwise**: every registered scenario at
   severity 0 reproduces the clean ``FormationEnv`` trajectory exactly
   (agents, goal, obs, rewards, dones) at identical seeds — the
   disturbance stack may add math to the program but never drift the
   clean path (layers are ``jnp.where``-guarded, not ``+ 0.0``).
2. **Compile-once**: scenario identity and severity are traced data, so
   ONE jitted train step serves a whole severity schedule with zero
   recompiles, and ONE jitted eval step serves every scenario x severity
   x same-architecture checkpoint (budget-1 RetraceGuard on both).
"""

import dataclasses
import json
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

# Force the threefry-partitionable flag BEFORE any draws: the knn path
# lazily imports jax_compat (which flips it), and a bitwise-identity test
# must not compare streams drawn on both sides of that flip.
from marl_distributedformation_tpu import jax_compat  # noqa: F401
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.formation import (
    reset_batch,
    step_batch,
)
from marl_distributedformation_tpu.scenarios import (
    ScenarioSchedule,
    ScenarioSpec,
    ScenarioStage,
    broadcast_params,
    get_scenario,
    register_scenario,
    registered_scenarios,
    sample_scenario_batch,
    scenario_step_batch,
    schedule_from_cfg,
)

M, N, STEPS = 3, 4, 8
PARAMS = EnvParams(num_agents=N, max_steps=6)


_ROW_FIELDS = ("agents", "goal", "obstacles", "obs", "reward", "done")


def _rollout(params, step_fn, num_steps=STEPS, m=M, seed=0):
    """Drive ``step_fn(state, velocity)`` with a shared random action
    stream; returns per-step ``_ROW_FIELDS`` tuples (obstacles included
    so the moving-obstacle layer has a recorded discriminator)."""
    state = reset_batch(jax.random.PRNGKey(seed), params, m)
    key = jax.random.PRNGKey(7)
    rows = []
    for _ in range(num_steps):
        key, k_act = jax.random.split(key)
        vel = params.max_speed * jax.random.uniform(
            k_act, (m, params.num_agents, 2), minval=-1.0, maxval=1.0
        )
        state, tr = step_fn(state, vel)
        rows.append(
            jax.device_get(
                (
                    state.agents, state.goal, state.obstacles,
                    tr.obs, tr.reward, tr.done,
                )
            )
        )
    return rows


def _scenario_step_fn(params, name, severity, m=M):
    sp = broadcast_params(
        get_scenario(name).build(jnp.float32(severity)), m
    )
    return lambda state, vel: scenario_step_batch(state, vel, sp, params)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_has_a_real_scenario_suite():
    names = registered_scenarios()
    assert len(names) >= 5
    assert "clean" in names
    # The ISSUE's named capabilities all have a registered carrier.
    for required in (
        "actuator_fault", "sensor_noise", "wind", "moving_goal",
        "goal_switch", "comm_dropout",
    ):
        assert required in names


def test_unknown_scenario_fails_fast_naming_registry():
    with pytest.raises(ValueError) as e:
        get_scenario("windd")
    msg = str(e.value)
    assert "did you mean 'wind'" in msg
    for name in registered_scenarios():
        assert name in msg, "the error must list every valid entry"


def test_register_scenario_refuses_silent_overwrite():
    with pytest.raises(ValueError):
        register_scenario(ScenarioSpec(name="clean"))


# ---------------------------------------------------------------------------
# Severity-0 identity (bitwise) + severity>0 actually perturbs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", registered_scenarios())
def test_severity_zero_is_bitwise_clean_trajectory(name):
    clean = _rollout(PARAMS, lambda s, v: step_batch(s, v, PARAMS))
    scen = _rollout(PARAMS, _scenario_step_fn(PARAMS, name, 0.0))
    for t, (c_row, s_row) in enumerate(zip(clean, scen)):
        for c, s, what in zip(c_row, s_row, _ROW_FIELDS):
            assert np.array_equal(np.asarray(c), np.asarray(s)), (
                f"{name} severity=0 diverged from clean at step {t} "
                f"({what}) — must be bitwise identical"
            )


@pytest.mark.parametrize(
    "name", [n for n in registered_scenarios() if n != "clean"]
)
def test_severity_one_perturbs_the_trajectory(name):
    # The obstacle layers are (documented) identities on an env with no
    # obstacles — give them something to move / occlude behind.
    params = (
        dataclasses.replace(PARAMS, num_obstacles=4)
        if name in ("obstacle_field", "moving_obstacles")
        else PARAMS
    )
    clean = _rollout(params, lambda s, v: step_batch(s, v, params))
    scen = _rollout(params, _scenario_step_fn(params, name, 1.0))
    assert any(
        not np.array_equal(np.asarray(c), np.asarray(s))
        for c_row, s_row in zip(clean, scen)
        for c, s in zip(c_row, s_row)
    ), f"{name} at severity 1 must change the trajectory"


def test_severity_zero_identity_knn_obs_mode():
    """The knn batched-obs routing (with_obs=False + batch-wide search)
    must preserve the identity too — it is a separate code path."""
    params = EnvParams(num_agents=5, max_steps=6, obs_mode="knn", knn_k=2)
    clean = _rollout(params, lambda s, v: step_batch(s, v, params))
    scen = _rollout(params, _scenario_step_fn(params, "storm", 0.0))
    for c_row, s_row in zip(clean, scen):
        for c, s in zip(c_row, s_row):
            assert np.array_equal(np.asarray(c), np.asarray(s))


def test_comm_dropout_masks_only_neighbor_columns():
    """At drop prob 1.0 every neighbor-derived column is zero while own
    position (and the relative goal) stay untouched."""
    from marl_distributedformation_tpu.scenarios import (
        neighbor_obs_columns,
    )

    sp = broadcast_params(
        get_scenario("comm_dropout").build(jnp.float32(2.0)), M
    )  # 0.5 * 2.0 -> clipped to prob 1.0
    assert float(sp.comm_drop_prob[0]) == 1.0
    state = reset_batch(jax.random.PRNGKey(0), PARAMS, M)
    vel = jnp.zeros((M, N, 2), jnp.float32)
    _, tr_clean = step_batch(state, vel, PARAMS)
    _, tr = scenario_step_batch(state, vel, sp, PARAMS)
    cols = neighbor_obs_columns(PARAMS)
    obs = np.asarray(tr.obs)
    assert np.all(obs[..., cols] == 0.0)
    assert np.array_equal(
        obs[..., ~cols], np.asarray(tr_clean.obs)[..., ~cols]
    )


# ---------------------------------------------------------------------------
# Domain-randomized batches
# ---------------------------------------------------------------------------


def test_mixed_scenario_batch_steps():
    specs = tuple(
        get_scenario(n) for n in ("clean", "wind", "sensor_noise")
    )
    probs = jnp.full((3,), 1.0 / 3.0, jnp.float32)
    sp = sample_scenario_batch(
        jax.random.PRNGKey(3), jnp.float32(0.7), probs, specs, M
    )
    assert sp.fault_prob.shape == (M,) and sp.wind.shape == (M, 2)
    state = reset_batch(jax.random.PRNGKey(0), PARAMS, M)
    vel = jnp.ones((M, N, 2), jnp.float32)
    _, tr = scenario_step_batch(state, vel, sp, PARAMS)
    assert np.isfinite(np.asarray(tr.obs)).all()


# ---------------------------------------------------------------------------
# Compile-once contracts
# ---------------------------------------------------------------------------


def test_scenario_train_step_compiles_exactly_once_across_schedule():
    """5 dispatches spanning a stage boundary and a severity ramp (and a
    scenario-mix change) = ONE compile of the jitted train iteration."""
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    schedule = ScenarioSchedule(
        stages=(
            ScenarioStage(rollouts=2, scenarios=("clean",), severity=0.0),
            ScenarioStage(
                rollouts=3,
                scenarios=(
                    "wind", "sensor_noise", "actuator_fault", "storm",
                ),
                severity=1.0,
            ),
        )
    )
    trainer = Trainer(
        EnvParams(num_agents=3, max_steps=5),
        ppo=PPOConfig(n_steps=2, batch_size=8, n_epochs=1),
        config=TrainConfig(
            num_formations=4, checkpoint=False, name="scenario_compile",
            guard_retraces=1,
        ),
        scenario_schedule=schedule,
    )
    severities = []
    for _ in range(5):
        metrics = trainer.run_iteration()
        severities.append(trainer.scenario_severity)
    assert trainer.retrace_guard.count == 1, (
        "severity/stage changes must never recompile the train step"
    )
    assert severities[-1] == 1.0, "the ramp must reach the stage target"
    assert np.isfinite(float(metrics["loss"]))


def test_matrix_eval_compiles_once_for_scenarios_x_severities_x_params():
    """One jitted eval step serves >=5 scenarios x >=3 severities x 2
    parameter sets (checkpoints of one architecture): budget-1 guard."""
    from marl_distributedformation_tpu.models import MLPActorCritic
    from marl_distributedformation_tpu.scenarios import make_matrix_runner

    params = EnvParams(num_agents=3, max_steps=5)
    model = MLPActorCritic(act_dim=2)
    dummy = jnp.zeros((1, params.obs_dim), jnp.float32)
    param_sets = [
        model.init(jax.random.PRNGKey(i), dummy) for i in range(2)
    ]
    run, guard = make_matrix_runner(model, params, num_formations=4)
    key = jax.random.PRNGKey(11)
    names = ("clean", "wind", "sensor_noise", "actuator_fault", "storm")
    for model_params in param_sets:
        for name in names:
            for severity in (0.0, 0.5, 1.0):
                out = run(
                    key, model_params,
                    get_scenario(name).build(jnp.float32(severity)),
                )
    assert guard.count == 1
    assert np.isfinite(float(out["episode_return_per_agent"]))


# ---------------------------------------------------------------------------
# Schedule parsing
# ---------------------------------------------------------------------------


def test_schedule_from_names_list():
    schedule = schedule_from_cfg(["wind", "storm"], default_severity=0.3)
    assert schedule.names == ("wind", "storm")
    assert schedule.severity_at(0) == pytest.approx(0.3)
    assert schedule.severity_at(99) == pytest.approx(0.3)


def test_schedule_from_stage_dicts_ramps_and_holds():
    schedule = schedule_from_cfg(
        "[{rollouts: 2, scenarios: [clean]},"
        " {rollouts: 3, scenarios: [wind], severity: 1.0}]",
        default_severity=0.5,
    )
    assert schedule.total_rollouts == 5
    assert schedule.names == ("clean", "wind")
    # Stage 2 ramps from stage 1's end (0.5) to 1.0 over 3 rollouts.
    assert schedule.severity_at(2) == pytest.approx(0.5)
    assert schedule.severity_at(4) == pytest.approx(1.0)
    assert schedule.severity_at(50) == pytest.approx(1.0)  # holds
    probs = schedule.probs_at(3)
    assert probs.tolist() == [0.0, 1.0]


def test_schedule_rejects_unknown_scenarios_and_keys():
    with pytest.raises(ValueError, match="registered scenarios"):
        schedule_from_cfg(["warp_drive"])
    with pytest.raises(ValueError, match="unknown scenario-stage keys"):
        schedule_from_cfg([{"rollouts": 1, "scenario": ["wind"]}])


# ---------------------------------------------------------------------------
# Robustness matrix CLI + evaluate.py fail-fast
# ---------------------------------------------------------------------------


def _train_tiny_run(tmp_path, name="matrixrun"):
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    trainer = Trainer(
        EnvParams(num_agents=3, max_steps=5),
        ppo=PPOConfig(n_steps=2, batch_size=8, n_epochs=1),
        config=TrainConfig(
            num_formations=4, checkpoint=True, name=name,
            log_dir=str(tmp_path / "logs" / name),
        ),
    )
    trainer.run_iteration()
    trainer.save()
    trainer.run_iteration()
    trainer.save()
    return trainer


def test_robustness_matrix_cli_emits_json(tmp_path, monkeypatch, capsys):
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))
    monkeypatch.setattr(
        "marl_distributedformation_tpu.utils.repo_root", lambda: tmp_path
    )
    monkeypatch.setattr(
        "marl_distributedformation_tpu.utils.config.repo_root",
        lambda: tmp_path,
    )
    import shutil

    (tmp_path / "cfg").mkdir()
    shutil.copy(
        Path(__file__).resolve().parent.parent / "cfg" / "config.yaml",
        tmp_path / "cfg" / "config.yaml",
    )
    _train_tiny_run(tmp_path)

    import robustness_matrix as rm

    monkeypatch.setattr(rm, "repo_root", lambda: tmp_path)
    report = rm.main(
        [
            "name=matrixrun",
            "num_agents_per_formation=3",
            "max_steps=5",
            "eval_formations=4",
        ]
    )
    # Acceptance shape: >= 5 scenarios x 2 checkpoints, one compile.
    assert len(report["scenarios"]) >= 5
    assert len(report["checkpoints"]) == 2
    assert len(report["severities"]) >= 3
    assert report["eval_compiles"] == 1
    on_disk = json.loads(Path(report["out"]).read_text())
    assert set(on_disk["matrix"]) == set(report["checkpoints"])
    cell = next(iter(next(iter(on_disk["matrix"].values())).values()))
    assert "episode_return_per_agent" in next(iter(cell.values()))
    # The stdout JSON line parses (bench.py contract style).
    last = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(last)["eval_compiles"] == 1

    with pytest.raises(SystemExit, match="registered scenarios"):
        rm.main(["name=matrixrun", "scenarios=[windd]"])


def test_evaluate_cli_fails_fast_on_unknown_scenario_and_key():
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import evaluate as evaluate_cli

    with pytest.raises(SystemExit, match="registered scenarios"):
        evaluate_cli.main(["name=x", "scenario=warp_drive"])
    with pytest.raises(SystemExit, match="eval_formations"):
        evaluate_cli.main(["name=x", "eval_formatoins=8"])
    # Near-misses that ARE valid YAML keys but would silently evaluate
    # the clean env: the plural training key, and a severity without a
    # scenario to apply it to.
    with pytest.raises(SystemExit, match="SINGULAR scenario="):
        evaluate_cli.main(["name=x", "scenarios=wind"])
    with pytest.raises(SystemExit, match="without scenario="):
        evaluate_cli.main(["name=x", "scenario_severity=1.0"])


def test_scenario_schedule_survives_resume(tmp_path):
    """resume=true must re-enter the schedule at the restored rollout
    index — not replay the severity ramp from stage 0."""
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    schedule = ScenarioSchedule(
        stages=(
            ScenarioStage(rollouts=2, scenarios=("clean",), severity=0.0),
            ScenarioStage(rollouts=4, scenarios=("storm",), severity=1.0),
        )
    )

    def make(resume):
        return Trainer(
            EnvParams(num_agents=3, max_steps=5),
            ppo=PPOConfig(n_steps=2, batch_size=8, n_epochs=1),
            config=TrainConfig(
                num_formations=4, checkpoint=True, name="scenario_resume",
                log_dir=str(tmp_path / "logs" / "scenario_resume"),
                resume=resume,
            ),
            scenario_schedule=schedule,
        )

    trainer = make(resume=False)
    for _ in range(4):  # land mid-way through the storm stage's ramp
        trainer.run_iteration()
    trainer.save()
    resumed = make(resume=True)
    assert resumed._scenario_rollouts == 4
    assert resumed.scenario_severity == pytest.approx(
        schedule.severity_at(4)
    )
    assert resumed.scenario_severity > 0.0, "must not restart at stage 0"
    # The sampling stream is a pure function of (seed, rollout index):
    # the resumed draw equals the uninterrupted run's draw for rollout 4
    # (not a replay of rollout 0's).
    for resumed_leaf, live_leaf in zip(
        jax.tree_util.tree_leaves(resumed.scenario_params),
        jax.tree_util.tree_leaves(trainer.scenario_params),
    ):
        assert np.array_equal(
            np.asarray(resumed_leaf), np.asarray(live_leaf)
        )


def test_schedule_rejects_zero_rollout_stage():
    with pytest.raises(ValueError, match="rollouts must be positive"):
        schedule_from_cfg([{"rollouts": 0, "scenarios": ["wind"]}])


def test_evaluate_scenario_shifts_baseline_returns():
    """The public eval entry under a scenario: same seed, same act_fn —
    wind at severity 1 must change the baseline controller's return."""
    from marl_distributedformation_tpu.eval import (
        baseline_act_fn,
        evaluate,
        evaluate_scenario,
    )

    clean = evaluate(
        baseline_act_fn(PARAMS), PARAMS, num_formations=4, seed=5
    )
    windy = evaluate_scenario(
        baseline_act_fn(PARAMS), PARAMS, "wind", 1.0,
        num_formations=4, seed=5,
    )
    zero = evaluate_scenario(
        baseline_act_fn(PARAMS), PARAMS, "wind", 0.0,
        num_formations=4, seed=5,
    )
    assert zero == clean, "severity 0 must reproduce the clean eval"
    assert windy["episode_return_per_agent"] != clean[
        "episode_return_per_agent"
    ]


def test_serving_smoke_rejects_unknown_scenario():
    """The smoke's scenario hook resolves the registry BEFORE touching
    the scheduler — a typo fails fast, never a clean-noise run."""
    from marl_distributedformation_tpu.serving.smoke import (
        run_smoke_benchmark,
    )

    with pytest.raises(ValueError, match="registered scenarios"):
        run_smoke_benchmark(None, row_shape=(8,), scenario="windd")
