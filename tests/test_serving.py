"""Serving subsystem contract (tier-1, CPU): compiled bucket ladder,
micro-batching scheduler, hot-reload registry, and the checkpoint edges
the hot-reload path leans on.

The acceptance pins from the serving ISSUE live here:

- a mixed stream of request sizes spanning >= 3 buckets compiles each
  bucket exactly once (asserted through the engine's RetraceGuards);
- a checkpoint hot-swap mid-stream changes subsequent actions without
  dropping or corrupting any in-flight request, and never recompiles;
- the smoke benchmark reports batch occupancy and p50/p95 latency.
"""

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

from marl_distributedformation_tpu.compat.policy import (  # noqa: E402
    LoadedPolicy,
    load_checkpoint_raw,
)
from marl_distributedformation_tpu.models import MLPActorCritic  # noqa: E402
from marl_distributedformation_tpu.serving import (  # noqa: E402
    BackpressureError,
    BucketedPolicyEngine,
    MicroBatchScheduler,
    ModelRegistry,
    RequestTimeout,
    ServingClient,
    run_smoke_benchmark,
)
from marl_distributedformation_tpu.utils.checkpoint import (  # noqa: E402
    latest_checkpoint,
    restore_checkpoint_partial,
    save_checkpoint,
)

OBS_DIM = 6
HIDDEN = (8, 8)


def _make_policy(seed=0, hidden=HIDDEN, obs_dim=OBS_DIM):
    model = MLPActorCritic(act_dim=2, hidden=hidden)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim)))
    return LoadedPolicy(dict(variables), model_kwargs={"hidden": hidden})


def _write_ckpt(log_dir, step, policy):
    """A trainer-shaped checkpoint file (policy name + variables)."""
    return save_checkpoint(
        log_dir,
        step,
        {
            "policy": type(policy.model).__name__,
            "params": policy.params,
            "num_timesteps": step,
        },
    )


def _obs(n, seed=0):
    return (
        np.random.default_rng(seed)
        .standard_normal((n, OBS_DIM))
        .astype(np.float32)
    )


# ---------------------------------------------------------------------------
# Engine: bucket ladder + compile-once pin
# ---------------------------------------------------------------------------


def test_engine_matches_loaded_policy_predict():
    policy = _make_policy()
    engine = BucketedPolicyEngine(policy, buckets=(1, 8, 64))
    for n in (1, 3, 8):
        obs = _obs(n, seed=n)
        ref, _ = policy.predict(obs, deterministic=True)
        np.testing.assert_allclose(
            engine.act(obs, deterministic=True), ref, rtol=1e-5, atol=1e-6
        )


def test_engine_mixed_stream_compiles_each_bucket_exactly_once():
    """The serving contract: any mix of request sizes spanning the whole
    ladder costs exactly one compile per rung, ever (RetraceGuard budget
    1 — a second trace would raise, not just fail the count check)."""
    engine = BucketedPolicyEngine(
        _make_policy(), buckets=(1, 8, 64), max_traces_per_bucket=1
    )
    # Sizes straddle all three rungs, incl. the split path (> top rung)
    # and both deterministic modes over the same rung.
    for i, (n, det) in enumerate(
        [(1, True), (2, True), (8, False), (9, True), (40, False),
         (64, True), (65, True), (130, False), (1, False), (5, True)]
    ):
        actions = engine.act(_obs(n, seed=i), deterministic=det)
        assert actions.shape == (n, 2)
        assert np.abs(actions).max() <= 1.0 + 1e-6
    assert engine.compile_counts() == {1: 1, 8: 1, 64: 1}


def test_engine_split_path_matches_direct_apply():
    """Requests above the top bucket split into chunks; padding and
    splitting must be invisible in the numbers."""
    policy = _make_policy()
    engine = BucketedPolicyEngine(policy, buckets=(1, 8, 64))
    obs = _obs(130, seed=3)
    ref, _ = policy.predict(obs, deterministic=True)
    np.testing.assert_allclose(engine.act(obs), ref, rtol=1e-5, atol=1e-6)


def test_engine_stochastic_draws_fresh_keys():
    engine = BucketedPolicyEngine(_make_policy(), buckets=(8,))
    obs = _obs(4, seed=1)
    a1 = engine.act(obs, deterministic=False)
    a2 = engine.act(obs, deterministic=False)
    assert not np.allclose(a1, a2), "same key consumed twice"
    assert np.abs(a1).max() <= 1.0 + 1e-6  # clipped to the action space


def test_engine_rejects_rowless_and_unbatched_obs():
    engine = BucketedPolicyEngine(_make_policy(), buckets=(8,))
    with pytest.raises(ValueError, match="leading batch axis"):
        engine.act(np.zeros(OBS_DIM, np.float32))
    with pytest.raises(ValueError, match="at least one row"):
        engine.act(np.zeros((0, OBS_DIM), np.float32))


# ---------------------------------------------------------------------------
# Scheduler: coalescing, backpressure, timeouts
# ---------------------------------------------------------------------------


def test_scheduler_coalesces_and_answers_each_request():
    policy = _make_policy()
    engine = BucketedPolicyEngine(policy, buckets=(1, 8, 64))
    sched = MicroBatchScheduler(engine, window_ms=10.0)
    sizes = [1, 3, 5, 8, 2, 7, 4, 6]
    with sched:
        futures = [
            sched.submit(_obs(n, seed=10 + i), deterministic=True)
            for i, n in enumerate(sizes)
        ]
        results = [f.result(timeout=30) for f in futures]
    for i, (n, res) in enumerate(zip(sizes, results)):
        ref, _ = policy.predict(_obs(n, seed=10 + i), deterministic=True)
        np.testing.assert_allclose(res.actions, ref, rtol=1e-5, atol=1e-6)
        assert res.latency_s >= 0.0
    m = sched.metrics
    assert m.requests_total == len(sizes)
    assert m.rows_total == sum(sizes)
    # The 10ms window actually coalesced (requests were enqueued
    # back-to-back, far faster than the window).
    assert m.batches_total < len(sizes)
    assert m.padded_rows_total >= m.rows_total


def test_scheduler_mixed_deterministic_flags_split_correctly():
    policy = _make_policy()
    engine = BucketedPolicyEngine(policy, buckets=(1, 8, 64))
    with MicroBatchScheduler(engine, window_ms=10.0) as sched:
        f_det = sched.submit(_obs(3, seed=1), deterministic=True)
        f_sto = sched.submit(_obs(3, seed=1), deterministic=False)
        det = f_det.result(timeout=30).actions
        sto = f_sto.result(timeout=30).actions
    ref, _ = policy.predict(_obs(3, seed=1), deterministic=True)
    np.testing.assert_allclose(det, ref, rtol=1e-5, atol=1e-6)
    assert not np.allclose(sto, ref), "stochastic group got the mode action"


def _slow_engine(engine, delay_s):
    """Wrap engine.act with a delay so the worker stays busy and the
    queue actually fills (backpressure/timeout tests)."""
    orig = engine.act

    def slow_act(*args, **kwargs):
        time.sleep(delay_s)
        return orig(*args, **kwargs)

    engine.act = slow_act
    return engine


def test_scheduler_backpressure_rejects_with_retry_after():
    engine = _slow_engine(
        BucketedPolicyEngine(_make_policy(), buckets=(8,)), 0.2
    )
    with MicroBatchScheduler(engine, max_queue=2, window_ms=0.0) as sched:
        futures, rejected = [], None
        # The worker is stuck ~200ms per batch; more submits than the
        # queue holds must hit the bound.
        for i in range(10):
            try:
                futures.append(sched.submit(_obs(2, seed=i)))
            except BackpressureError as e:
                rejected = e
                break
        assert rejected is not None, "queue bound never engaged"
        assert rejected.retry_after_s > 0.0
        assert sched.metrics.rejected_total >= 1
        for f in futures:  # accepted requests still complete
            assert f.result(timeout=30).actions.shape == (2, 2)


def test_scheduler_expires_timed_out_requests():
    engine = _slow_engine(
        BucketedPolicyEngine(_make_policy(), buckets=(8,)), 0.25
    )
    with MicroBatchScheduler(engine, window_ms=0.0) as sched:
        blocker = sched.submit(_obs(1, seed=0))  # occupies the worker
        doomed = sched.submit(_obs(1, seed=1), timeout_s=0.01)
        with pytest.raises(RequestTimeout):
            doomed.result(timeout=30)
        assert blocker.result(timeout=30).actions.shape == (1, 2)
        assert sched.metrics.timeouts_total == 1


def test_scheduler_survives_mismatched_row_shapes():
    """One client's malformed rows must fail only that client's future —
    never the coalesced neighbors, never the worker thread."""
    policy = _make_policy()
    engine = BucketedPolicyEngine(policy, buckets=(1, 8, 64))
    with MicroBatchScheduler(engine, window_ms=20.0) as sched:
        good = sched.submit(_obs(2, seed=1))
        bad = sched.submit(
            np.zeros((2, OBS_DIM + 1), np.float32)  # wrong trailing shape
        )
        ref, _ = policy.predict(_obs(2, seed=1), deterministic=True)
        np.testing.assert_allclose(
            good.result(timeout=30).actions, ref, rtol=1e-5, atol=1e-6
        )
        with pytest.raises(Exception):
            bad.result(timeout=30)
        # The worker is still alive and serving.
        again = sched.submit(_obs(3, seed=2))
        assert again.result(timeout=30).actions.shape == (3, 2)


def test_malformed_first_request_does_not_poison_the_bucket():
    """The nastier ordering: the very FIRST request to a bucket is
    malformed. Its failed trace must not consume the budget-1
    RetraceGuard — valid requests on the same rung must still compile
    and serve afterwards."""
    policy = _make_policy()
    engine = BucketedPolicyEngine(
        policy, buckets=(8,), max_traces_per_bucket=1
    )
    with pytest.raises(Exception):
        engine.act(np.zeros((2, OBS_DIM + 1), np.float32))
    assert engine.compile_counts() == {8: 0}, (
        "a failed trace is not a compilation"
    )
    obs = _obs(2, seed=1)
    ref, _ = policy.predict(obs, deterministic=True)
    np.testing.assert_allclose(
        engine.act(obs), ref, rtol=1e-5, atol=1e-6
    )
    assert engine.compile_counts() == {8: 1}
    # With a row shape established, later mismatches fail fast (a
    # ValueError before any jit machinery) instead of burning a trace.
    with pytest.raises(ValueError, match="one compiled row shape"):
        engine.act(np.zeros((2, OBS_DIM + 1), np.float32))


# ---------------------------------------------------------------------------
# Registry: hot swap, version pinning, bad-checkpoint containment
# ---------------------------------------------------------------------------


def test_hot_swap_mid_stream_no_drops_no_recompiles(tmp_path):
    """The acceptance pin: a swap mid-stream changes subsequent actions,
    drops nothing, and reuses the compiled programs (params are an
    argument, not a closure)."""
    pol_a, pol_b = _make_policy(seed=0), _make_policy(seed=7)
    _write_ckpt(tmp_path, 100, pol_a)
    registry = ModelRegistry(tmp_path)
    engine = BucketedPolicyEngine(
        registry.policy, buckets=(1, 8, 64), max_traces_per_bucket=1
    )
    obs = _obs(5, seed=5)
    ref_a, _ = pol_a.predict(obs, deterministic=True)
    ref_b, _ = pol_b.predict(obs, deterministic=True)
    assert not np.allclose(ref_a, ref_b)

    with MicroBatchScheduler(engine, registry=registry, window_ms=1.0) as s:
        first = [s.submit(obs) for _ in range(8)]
        first_results = [f.result(timeout=30) for f in first]
        # Swap lands while the server keeps accepting work.
        inflight = [s.submit(obs) for _ in range(8)]
        _write_ckpt(tmp_path, 200, pol_b)
        assert registry.refresh(), "newer checkpoint must swap"
        second = [s.submit(obs) for _ in range(8)]
        inflight_results = [f.result(timeout=30) for f in inflight]
        second_results = [f.result(timeout=30) for f in second]

    for res in first_results:
        assert res.model_step == 100
        np.testing.assert_allclose(res.actions, ref_a, rtol=1e-5, atol=1e-6)
    # In-flight requests must all resolve, each answered consistently by
    # exactly ONE version (never a torn mix), whichever side of the swap
    # their batch dispatched on.
    for res in inflight_results:
        assert res.model_step in (100, 200)
        ref = ref_a if res.model_step == 100 else ref_b
        np.testing.assert_allclose(res.actions, ref, rtol=1e-5, atol=1e-6)
    for res in second_results:
        assert res.model_step == 200
        np.testing.assert_allclose(res.actions, ref_b, rtol=1e-5, atol=1e-6)
    assert registry.swap_count == 1
    # Budget-1 guards would have raised on any recompile; the counts
    # document it.
    assert all(c <= 1 for c in engine.compile_counts().values())


def test_registry_ignores_older_and_equal_steps(tmp_path):
    pol = _make_policy()
    _write_ckpt(tmp_path, 50, pol)
    registry = ModelRegistry(tmp_path)
    assert registry.active_step == 50
    assert not registry.refresh()  # same file
    _write_ckpt(tmp_path, 40, _make_policy(seed=9))
    assert not registry.refresh()  # older step: latest is still 50
    assert registry.active_step == 50


def test_registry_keeps_serving_on_mismatched_architecture(tmp_path):
    _write_ckpt(tmp_path, 10, _make_policy(hidden=(8, 8)))
    registry = ModelRegistry(tmp_path)
    params_before, step_before = registry.active()
    # A wider tower lands in the watch directory (operator error).
    _write_ckpt(tmp_path, 20, _make_policy(hidden=(16, 16)))
    assert not registry.refresh()
    assert registry.active_step == step_before == 10
    assert registry.active()[0] is params_before
    assert len(registry.load_errors) == 1
    path, err = registry.load_errors[0]
    assert "rl_model_20_steps" in path
    assert "architecture mismatch" in err


def test_registry_with_prebuilt_policy_upgrades_to_disk(tmp_path):
    """A pre-built policy has unknown provenance (step 0): the first
    refresh must adopt the newest on-disk checkpoint instead of treating
    its step as already served."""
    disk_policy = _make_policy(seed=3)
    _write_ckpt(tmp_path, 200, disk_policy)
    registry = ModelRegistry(tmp_path, policy=_make_policy(seed=0))
    assert registry.active_step == 0
    assert registry.refresh()
    assert registry.active_step == 200


def test_registry_params_live_on_device(tmp_path):
    """Swapped params must be device-resident (one upload at swap time),
    not the host numpy trees msgpack restores — a per-batch weight
    upload is the hot-loop poison the transfer guards exist for."""
    _write_ckpt(tmp_path, 1, _make_policy(seed=0))
    registry = ModelRegistry(tmp_path)
    _write_ckpt(tmp_path, 2, _make_policy(seed=1))
    assert registry.refresh()
    leaves = jax.tree_util.tree_leaves(registry.active()[0])
    assert leaves and all(isinstance(x, jax.Array) for x in leaves)


def test_registry_rejects_same_shape_dtype_drift(tmp_path):
    """A same-architecture checkpoint at a drifted dtype must be refused
    at validation time: jit caches key on dtype, so serving it would
    retrace every bucket and trip the budget-1 RetraceGuards forever."""
    _write_ckpt(tmp_path, 10, _make_policy())
    registry = ModelRegistry(tmp_path)
    drifted = _make_policy(seed=2)
    drifted.params = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float64), drifted.params
    )
    _write_ckpt(tmp_path, 20, drifted)
    assert not registry.refresh()
    assert registry.active_step == 10
    assert "dtype" in registry.load_errors[0][1]


def test_registry_background_watcher_swaps(tmp_path):
    _write_ckpt(tmp_path, 1, _make_policy(seed=0))
    registry = ModelRegistry(tmp_path, poll_interval_s=0.05)
    with registry:
        _write_ckpt(tmp_path, 2, _make_policy(seed=1))
        deadline = time.time() + 10.0
        while registry.active_step != 2 and time.time() < deadline:
            time.sleep(0.02)
    assert registry.active_step == 2
    assert registry.swap_count == 1


# ---------------------------------------------------------------------------
# Checkpoint hot-reload edges (utils.checkpoint)
# ---------------------------------------------------------------------------


def test_latest_checkpoint_never_observes_partial_writes(tmp_path):
    """Discovery racing the atomic writer: every path latest_checkpoint
    returns must parse completely (the dot-prefixed .tmp + rename
    protocol is the hot-reload foundation)."""
    # Big enough that a non-atomic write would have a wide torn window.
    target = {"params": {"w": np.arange(50_000, dtype=np.float32)}}
    done = threading.Event()

    def writer():
        for step in range(1, 120):
            save_checkpoint(tmp_path, step, target)
        done.set()

    t = threading.Thread(target=writer)
    t.start()
    reads = 0
    try:
        while not done.is_set():
            path = latest_checkpoint(tmp_path)
            if path is None:
                continue
            raw = load_checkpoint_raw(path)  # raises on a torn file
            assert "params" in raw
            reads += 1
    finally:
        t.join(timeout=60)
    assert reads > 0, "reader never overlapped the writer"


def test_latest_checkpoint_skips_temp_files(tmp_path):
    save_checkpoint(tmp_path, 7, {"x": np.zeros(3)})
    # A crashed writer's leftovers with bigger step numbers.
    (tmp_path / ".rl_model_999_steps.msgpack.tmp").write_bytes(b"torn")
    (tmp_path / "rl_model_888_steps.msgpack.tmp").write_bytes(b"torn")
    found = latest_checkpoint(tmp_path)
    assert found is not None and found.name == "rl_model_7_steps.msgpack"


def test_restore_partial_mismatched_shapes_is_a_clean_error(tmp_path):
    path = _write_ckpt(tmp_path, 5, _make_policy(hidden=(8, 8)))
    template = {"params": _make_policy(hidden=(16, 16)).params}
    with pytest.raises(ValueError, match="architecture mismatch") as e:
        restore_checkpoint_partial(path, template)
    assert "pi_0" in str(e.value)  # names the offending leaf
    assert "rl_model_5_steps" in str(e.value)  # and the file


def test_restore_partial_dict_where_array_is_a_clean_error():
    """from_state_dict restores a dict-where-array drift VERBATIM (the
    template leaf is simply replaced by the deeper dict), so the
    validation must compare tree structures, not just zip leaves."""
    from marl_distributedformation_tpu.utils.checkpoint import (
        restore_state_dict_partial,
    )

    template = {"params": {"w": np.zeros(3, np.float32)}}
    deeper = {
        "params": {
            "w": {"sub": np.zeros(3, np.float32),
                  "sub2": np.zeros(3, np.float32)}
        }
    }
    with pytest.raises(ValueError, match="tree structure"):
        restore_state_dict_partial(deeper, template, origin="drifted.msgpack")
    # And the inverse (array where a dict subtree belongs) is a clean
    # ValueError naming the origin, not a bare AttributeError.
    flat = {"params": np.zeros(3, np.float32)}
    nested_template = {"params": {"w": np.zeros(3, np.float32)}}
    with pytest.raises(ValueError, match="flat.msgpack"):
        restore_state_dict_partial(flat, nested_template, origin="flat.msgpack")


def test_restore_partial_mismatched_structure_is_a_clean_error(tmp_path):
    path = _write_ckpt(tmp_path, 5, _make_policy())
    other = MLPActorCritic(act_dim=2, hidden=(8, 8, 8))  # extra layer
    template = {
        "params": dict(
            other.init(jax.random.PRNGKey(0), jnp.zeros((1, OBS_DIM)))
        )
    }
    with pytest.raises(ValueError, match="rl_model_5_steps"):
        restore_checkpoint_partial(path, template)


# ---------------------------------------------------------------------------
# Client retry behavior
# ---------------------------------------------------------------------------


def test_backoff_is_capped_exponential_with_retry_after_floor():
    from marl_distributedformation_tpu.serving import backoff_s

    # The server hint is a FLOOR: sleeping less guarantees a re-reject.
    assert backoff_s(0, retry_after_s=0.5, base_s=0.05) == 0.5
    assert backoff_s(5, retry_after_s=3.0, base_s=0.05, cap_s=2.0) == 3.0
    # The exponential leg grows 2^attempt from base while the hint is
    # small (the server underestimating its own congestion)...
    assert backoff_s(0, retry_after_s=0.01, base_s=0.05) == 0.05
    assert backoff_s(1, retry_after_s=0.01, base_s=0.05) == 0.1
    assert backoff_s(2, retry_after_s=0.01, base_s=0.05) == 0.2
    # ...and is capped so a long retry ladder never sleeps for minutes.
    assert backoff_s(10, retry_after_s=0.01, base_s=0.05, cap_s=2.0) == 2.0


def test_backoff_full_jitter_spreads_the_stampede():
    """A fleet of clients hitting the same 429 must NOT wake in
    lockstep: with jitter, the sleep is a uniform random fraction of
    the capped-exponential delay — spread over the window, still
    floored at the server's retry_after, still bounded by the cap.
    Distribution pinned with a seeded RNG."""
    import random

    from marl_distributedformation_tpu.serving import backoff_s

    rng = random.Random(1234)
    cap = 2.0
    samples = [
        backoff_s(
            10, retry_after_s=0.01, base_s=0.05, cap_s=cap,
            jitter=rng.random,
        )
        for _ in range(500)
    ]
    # Floor and cap both hold for every draw.
    assert all(0.01 <= s <= cap for s in samples)
    # Full jitter means SPREAD, not a point mass at the cap (the
    # un-jittered value): many distinct values across the window, with
    # mass in the low, middle, and high thirds.
    assert len(set(samples)) > 400
    assert min(samples) < 0.2 and max(samples) > 1.8
    mean = sum(samples) / len(samples)
    assert 0.8 < mean < 1.2  # E[U(0,1)] * cap == cap/2, within noise
    # The floor still wins when the server prices a LONGER wait than
    # any jittered exponential draw.
    assert backoff_s(
        0, retry_after_s=3.0, base_s=0.05, cap_s=2.0, jitter=rng.random
    ) == 3.0
    # The client wires its own RNG through: jitter=False keeps the
    # deterministic ladder for single-caller tools.
    from marl_distributedformation_tpu.serving import ServingClient

    client = ServingClient(
        object(), jitter=True, rng=random.Random(7)
    )
    assert client.jitter and client._rng.random() == random.Random(
        7
    ).random()


def test_client_retries_through_backpressure_and_succeeds():
    """Opt-in retries absorb transient rejects: a client facing a full
    queue sleeps the (floored, capped-exponential) backoff and lands the
    request instead of surfacing BackpressureError to the caller."""
    engine = _slow_engine(
        BucketedPolicyEngine(_make_policy(), buckets=(8,)), 0.15
    )
    with MicroBatchScheduler(engine, max_queue=1, window_ms=0.0) as sched:
        client = ServingClient(
            sched, max_retries=8, backoff_base_s=0.02, backoff_cap_s=0.5
        )
        blockers = [sched.submit(_obs(1, seed=0))]  # worker + queue busy
        try:
            blockers.append(sched.submit(_obs(1, seed=1)))
        except BackpressureError:
            pass
        actions, _ = client.predict(_obs(2, seed=2))
        assert actions.shape == (2, 2)
        assert sched.metrics.rejected_total >= 1, (
            "the retry path was never exercised"
        )
        for f in blockers:
            assert f.result(timeout=30).actions.shape == (1, 2)


def test_client_retries_backpressure_delivered_through_the_future():
    """A fleet router can deliver BackpressureError through the FUTURE
    (failover landed on replicas that were all full) — it must consume
    retry budget exactly like a submit-time reject, not bypass the
    retry loop."""
    from concurrent.futures import Future

    from marl_distributedformation_tpu.serving import ServedResult

    class StubTarget:
        default_timeout_s = 1.0

        def __init__(self):
            self.calls = 0
            self.trace_ids = []

        def submit(self, obs, deterministic=True, timeout_s=None,
                   trace_id=None, slo_class="interactive"):
            self.calls += 1
            self.trace_ids.append(trace_id)
            future = Future()
            if self.calls == 1:
                future.set_exception(BackpressureError(0.01))
            else:
                future.set_result(
                    ServedResult(
                        actions=np.zeros((1, 2), np.float32),
                        model_step=5,
                        latency_s=0.0,
                    )
                )
            return future

    stub = StubTarget()
    client = ServingClient(stub, max_retries=2, backoff_base_s=0.001)
    result = client.predict_full(np.zeros((1, OBS_DIM), np.float32))
    assert result.model_step == 5
    assert stub.calls == 2, "the future-delivered reject must be retried"
    # ONE trace ID for the whole logical request: the client mints it
    # once and re-sends it on every retry attempt (obs/), so the
    # server-side batch spans of all attempts correlate.
    assert stub.trace_ids[0] is not None
    assert stub.trace_ids == [stub.trace_ids[0]] * 2
    # And with the budget exhausted, the reject surfaces.
    stub2 = StubTarget()
    with pytest.raises(BackpressureError):
        ServingClient(stub2, max_retries=0).predict_full(
            np.zeros((1, OBS_DIM), np.float32)
        )


def test_client_with_no_retries_surfaces_the_reject():
    engine = _slow_engine(
        BucketedPolicyEngine(_make_policy(), buckets=(8,)), 0.3
    )
    with MicroBatchScheduler(engine, max_queue=1, window_ms=0.0) as sched:
        client = ServingClient(sched, max_retries=0)
        futures = [sched.submit(_obs(1, seed=0))]
        # Wait for the worker to pick request 0 up (it then sleeps 0.3s
        # inside the slow engine) before refilling the queue — the queue
        # is then deterministically full when the client predicts, with
        # no race against the worker's wakeup.
        deadline = time.time() + 5.0
        while sched.queue_depth > 0 and time.time() < deadline:
            time.sleep(0.001)
        assert sched.queue_depth == 0, "worker never picked up request 0"
        futures.append(sched.submit(_obs(1, seed=1)))
        with pytest.raises(BackpressureError):
            client.predict(_obs(1, seed=2))
        for f in futures:
            assert f.result(timeout=30).actions.shape == (1, 2)


# ---------------------------------------------------------------------------
# Smoke benchmark + CLI
# ---------------------------------------------------------------------------


def test_smoke_benchmark_reports_occupancy_and_latency():
    engine = BucketedPolicyEngine(_make_policy(), buckets=(1, 8, 64))
    with MicroBatchScheduler(engine, window_ms=2.0) as sched:
        report = run_smoke_benchmark(
            sched,
            row_shape=(OBS_DIM,),
            sizes=(1, 5, 40),  # spans all three rungs
            duration_s=0.5,
            num_clients=3,
        )
    assert report["client_requests_ok"] > 0
    assert 0.0 < report["batch_occupancy_pct"] <= 100.0
    assert report["latency_p50_ms"] > 0.0
    assert report["latency_p95_ms"] >= report["latency_p50_ms"]
    for bucket in (1, 8, 64):
        assert report[f"compiles_bucket_{bucket}"] <= 1.0


def test_serve_policy_cli_smoke(tmp_path):
    _write_ckpt(tmp_path, 30, _make_policy())
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "serve_policy.py"),
            str(tmp_path),
            "--smoke",
            "--duration",
            "0.5",
            "--clients",
            "2",
            "--buckets",
            "1,8,64",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/local/bin:/usr/bin:/bin"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["client_requests_ok"] > 0
    assert report["batch_occupancy_pct"] > 0.0
    assert report["model_step"] == 30.0
    assert report["buckets"] == "1,8,64"
