"""Golden-parity tests: the JAX environment vs the actual reference code.

Loads the reference's ``FormationSimulator`` from /root/reference (read-only)
with a stubbed ``wandb`` module, forces identical states on both
implementations, and asserts obs/reward/done agreement to fp32 tolerance over
multi-step trajectories — the parity gate from SURVEY.md §7 step 2.

Skipped automatically if the reference checkout or torch is unavailable.
"""

import importlib.util
import sys
import types
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.env import (
    EnvParams,
    FormationState,
    compute_obs,
    control,
    reset,
    step,
)

REFERENCE_DIR = Path("/root/reference")

torch = pytest.importorskip("torch")

if not (REFERENCE_DIR / "simulate.py").exists():  # pragma: no cover
    pytest.skip("reference checkout unavailable", allow_module_level=True)


def _load_reference_simulate():
    """Import the reference simulate.py with wandb stubbed out."""
    if "wandb" not in sys.modules:
        stub = types.ModuleType("wandb")
        stub.log = lambda *a, **k: None
        stub.init = lambda *a, **k: None
        sys.modules["wandb"] = stub
    spec = importlib.util.spec_from_file_location(
        "_reference_simulate", REFERENCE_DIR / "simulate.py"
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


ref_sim = _load_reference_simulate()


def make_pair(num_agents, seed, share_reward_ratio=0.25, goal_in_obs=True):
    """Build (reference simulator, jax state, params) with identical state."""
    params = EnvParams(
        num_agents=num_agents,
        share_reward_ratio=share_reward_ratio,
        goal_in_obs=goal_in_obs,
    )
    sim = ref_sim.FormationSimulator(
        num_agents=num_agents,
        num_obstacles=0,
        share_reward_ratio=share_reward_ratio,
        goal_in_obs=goal_in_obs,
        visualize=False,
        log=False,
    )
    state = reset(jax.random.PRNGKey(seed), params)
    # Force the torch side onto the JAX side's sampled state.
    sim.agents = torch.tensor(np.asarray(state.agents), dtype=torch.float32)
    sim.goal = torch.tensor(np.asarray(state.goal), dtype=torch.float32)
    sim.obstacles = torch.zeros((0, 2))
    sim.steps_since_reset = 0
    return sim, state, params


@pytest.mark.parametrize("num_agents", [2, 3, 5, 20])
def test_step_parity_random_trajectory(num_agents):
    sim, state, params = make_pair(num_agents, seed=num_agents)
    rng = np.random.default_rng(0)
    for t in range(25):
        vel = rng.uniform(-10, 10, (num_agents, 2)).astype(np.float32)
        ref_obs, ref_rew, ref_done, _ = sim.step(torch.tensor(vel))
        state, tr = step(state, jnp.asarray(vel), params)
        assert bool(tr.done) == bool(ref_done)
        np.testing.assert_allclose(
            np.asarray(tr.reward),
            ref_rew.numpy(),
            rtol=1e-4,
            atol=1e-3,
            err_msg=f"reward diverged at t={t}",
        )
        np.testing.assert_allclose(
            np.asarray(tr.obs),
            ref_obs.numpy(),
            rtol=1e-4,
            atol=1e-5,
            err_msg=f"obs diverged at t={t}",
        )
        # Positions stay in lockstep, so drift cannot accumulate silently.
        np.testing.assert_allclose(
            np.asarray(state.agents), sim.agents.numpy(), rtol=1e-4, atol=1e-3
        )


def test_step_parity_extreme_actions_hit_bounds():
    sim, state, params = make_pair(4, seed=11)
    for vel in [
        np.full((4, 2), 1000.0, np.float32),  # slam into the top-right corner
        np.full((4, 2), -1000.0, np.float32),  # slam into the origin
        np.zeros((4, 2), np.float32),  # sit on the boundary (<=/>= flags)
    ]:
        ref_obs, ref_rew, ref_done, _ = sim.step(torch.tensor(vel))
        state, tr = step(state, jnp.asarray(vel), params)
        np.testing.assert_allclose(
            np.asarray(tr.reward), ref_rew.numpy(), rtol=1e-4, atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(tr.obs), ref_obs.numpy(), rtol=1e-4, atol=1e-5
        )


def test_step_parity_no_goal_in_obs():
    sim, state, params = make_pair(5, seed=3, goal_in_obs=False)
    vel = np.ones((5, 2), np.float32)
    ref_obs, ref_rew, _, _ = sim.step(torch.tensor(vel))
    state, tr = step(state, jnp.asarray(vel), params)
    assert tr.obs.shape == (5, 6) and ref_obs.shape == (5, 6)
    np.testing.assert_allclose(np.asarray(tr.obs), ref_obs.numpy(), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(tr.reward), ref_rew.numpy(), rtol=1e-4, atol=1e-3
    )


@pytest.mark.parametrize("rho", [0.0, 0.1, 0.5])
def test_reward_mixing_parity(rho):
    sim, state, params = make_pair(6, seed=7, share_reward_ratio=rho)
    vel = np.zeros((6, 2), np.float32)
    _, ref_rew, _, _ = sim.step(torch.tensor(vel))
    _, tr = step(state, jnp.asarray(vel), params)
    np.testing.assert_allclose(
        np.asarray(tr.reward), ref_rew.numpy(), rtol=1e-4, atol=1e-3
    )


def test_episode_length_parity():
    """Q1 measured end-to-end: both implementations run max_steps + 2 steps."""
    sim, state, params = make_pair(2, seed=1)
    sim.max_steps = 5
    params = params.replace(max_steps=5)
    zero = np.zeros((2, 2), np.float32)
    ref_done_at = jax_done_at = None
    for t in range(1, 12):
        _, _, ref_done, _ = sim.step(torch.tensor(zero))
        state, tr = step(state, jnp.asarray(zero), params)
        if ref_done and ref_done_at is None:
            ref_done_at = t
        if bool(tr.done) and jax_done_at is None:
            jax_done_at = t
    assert ref_done_at == jax_done_at == 7  # max_steps + 2


def test_baseline_controller_trajectory_parity():
    """The JAX potential-field controller reproduces the reference
    ``control`` trajectory (simulate.py:256-319) step for step."""
    num_agents = 10  # reference requires even N (simulate.py:279)
    sim, state, params = make_pair(num_agents, seed=42)
    for t in range(60):
        ref_sim.control(t, sim)  # steps the torch env internally
        vel = control(state.agents, state.goal, state.obstacles, params)
        state, tr = step(state, vel, params)
        np.testing.assert_allclose(
            np.asarray(state.agents),
            sim.agents.numpy(),
            rtol=1e-3,
            atol=5e-2,
            err_msg=f"baseline trajectory diverged at t={t}",
        )


def test_baseline_return_parity():
    """Return-parity gate (BASELINE.json config 1): total return of the JAX
    env+controller over a fixed horizon is within 1% of the reference's."""
    num_agents = 10
    # control() discards step outputs, so capture the velocity it would
    # apply via a recording proxy and step the torch env explicitly.
    sim2, state2, params = make_pair(num_agents, seed=123)
    ref_total = 0.0
    jax_total = 0.0
    for t in range(200):
        tvel = _torch_control_velocity(sim2)
        _, ref_rew, _, _ = sim2.step(tvel)
        ref_total += float(ref_rew.mean())
        vel = control(state2.agents, state2.goal, state2.obstacles, params)
        state2, tr = step(state2, vel, params)
        jax_total += float(tr.reward.mean())
    assert abs(jax_total - ref_total) <= 0.01 * abs(ref_total), (
        f"jax return {jax_total} vs reference {ref_total}"
    )


def _torch_control_velocity(sim):
    """Capture the velocity the reference controller would apply, by calling
    it against a recording proxy (control() both computes and steps)."""

    class _Recorder:
        def __init__(self, inner):
            self._inner = inner
            self.velocity = None

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def step(self, velocity):
            self.velocity = velocity

    rec = _Recorder(sim)
    ref_sim.control(0, rec)
    return rec.velocity
