"""Multi-tenant serving contract (tier-1, multi-device CPU): named
model lanes over ONE fleet.

The acceptance pins from the tenancy ISSUE live here, on the
8-virtual-device CPU mesh tests/conftest.py provisions:

- two same-arch formation lanes + one pursuit_evasion lane serve from
  ONE ``TenantFleet``; a batch storm on lane A leaves lane B's
  interactive traffic unrejected and per-lane step-monotonic;
- the ledger census shows shared rung executables — <= 1 compile per
  (arch, rung): same-arch lanes ride one set of compiled rungs
  (params are traced inputs), the distinct arch pays exactly its own
  budget-1 compile;
- a mid-storm coordinated swap of ONE lane commits (its served step
  advances, monotonically in completion order) without pausing any
  other lane's dispatch;
- admission is per-lane: one lane's full queue quotes ITS Retry-After
  while another lane's requests are still admitted;
- the HTTP frontend speaks ``model_id`` end to end — stamped on every
  act response, 400 with a did-you-mean for unknown lanes.
"""

import json
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.compat.policy import (  # noqa: E402
    LoadedPolicy,
)
from marl_distributedformation_tpu.models import MLPActorCritic  # noqa: E402
from marl_distributedformation_tpu.serving import (  # noqa: E402
    BackpressureError,
)
from marl_distributedformation_tpu.serving.fleet import (  # noqa: E402
    FleetFrontend,
)
from marl_distributedformation_tpu.serving.tenancy import (  # noqa: E402
    TenantDirectory,
    TenantSpec,
    TenantFleet,
    run_tenant_smoke,
    tenant_fleet_from_directory,
)
from marl_distributedformation_tpu.utils.checkpoint import (  # noqa: E402
    save_checkpoint,
)

REPO = Path(__file__).resolve().parent.parent

OBS_DIM = 8  # both registered envs' default rows are 8-wide
HIDDEN = (8, 8)


def _make_policy(seed=0, hidden=HIDDEN, obs_dim=OBS_DIM):
    model = MLPActorCritic(act_dim=2, hidden=hidden)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim)))
    return LoadedPolicy(dict(variables), model_kwargs={"hidden": hidden})


def _write_ckpt(log_dir, step, policy):
    return save_checkpoint(
        log_dir,
        step,
        {
            "policy": type(policy.model).__name__,
            "params": policy.params,
            "num_timesteps": step,
        },
    )


def _obs(n, seed=0):
    return (
        np.random.default_rng(seed)
        .standard_normal((n, OBS_DIM))
        .astype(np.float32)
    )


def _directory(tmp_path=None):
    """Two same-arch formation lanes + one distinct-arch pursuit lane.
    With a tmp_path, each lane gets its own promoted/ dir + seed ckpt."""
    specs = [
        TenantSpec(model_id="formation-a", env="formation", hidden=HIDDEN),
        TenantSpec(model_id="formation-b", env="formation", hidden=HIDDEN),
        TenantSpec(
            model_id="pursuit", env="pursuit_evasion", hidden=(16, 16)
        ),
    ]
    if tmp_path is None:
        return TenantDirectory(specs)
    out = []
    for i, spec in enumerate(specs):
        d = tmp_path / spec.model_id / "promoted"
        _write_ckpt(d, 100 * (i + 1), _make_policy(i, hidden=spec.hidden))
        out.append(
            TenantSpec(
                **{
                    **{
                        f.name: getattr(spec, f.name)
                        for f in spec.__dataclass_fields__.values()
                    },
                    "promoted_dir": d,
                }
            )
        )
    return TenantDirectory(out)


# ---------------------------------------------------------------------------
# Directory
# ---------------------------------------------------------------------------


def test_directory_validates_lane_declarations():
    # model_id grammar: it becomes a Prometheus label value and the
    # model_{id}__{metric} snapshot key, so "__" and junk are rejected.
    for bad in ("", "a__b", "-leading", "sp ace", "semi;colon"):
        with pytest.raises(ValueError, match="model_id"):
            TenantSpec(model_id=bad)
    with pytest.raises(ValueError, match="slo_class"):
        TenantSpec(model_id="a", slo_class="platinum")
    with pytest.raises(ValueError, match="policy"):
        TenantSpec(model_id="a", policy="TransformerXXL")
    # Misspelled env fails at DECLARATION time with the registry's
    # did-you-mean, not at first request.
    with pytest.raises(ValueError, match="did you mean 'formation'"):
        TenantSpec(model_id="a", env="fromation")
    d = TenantDirectory([TenantSpec(model_id="a")])
    with pytest.raises(ValueError, match="duplicate"):
        d.add(TenantSpec(model_id="a"))


def test_directory_lookup_and_arch_grouping():
    d = _directory()
    assert list(d) == ["formation-a", "formation-b", "pursuit"]
    with pytest.raises(KeyError, match="formation-a"):
        d.get("formation_a")  # did-you-mean names the close lane
    groups = d.arch_groups()
    assert len(groups) == 2  # two formation lanes share one signature
    sizes = sorted(len(specs) for specs in groups.values())
    assert sizes == [1, 2]
    (pursuit_arch,) = [
        arch
        for arch, specs in groups.items()
        if specs[0].model_id == "pursuit"
    ]
    assert "16x16" in pursuit_arch and "obs8" in pursuit_arch


def test_fleet_construction_is_fail_fast():
    d = _directory()
    policies = {
        "formation-a": _make_policy(0),
        "formation-b": _make_policy(1),
        "pursuit": _make_policy(2, hidden=(16, 16)),
    }
    with pytest.raises(ValueError, match="no seed policy"):
        TenantFleet(d, {k: policies[k] for k in ("formation-a", "pursuit")})
    with pytest.raises(ValueError, match="undeclared"):
        TenantFleet(d, {**policies, "ghost": _make_policy(3)})
    # A lane declaring the shared arch whose actual param tree differs
    # cannot ride the group's compiled rungs — caught at construction,
    # not as a shape crash inside a rung at first dispatch.
    with pytest.raises(ValueError, match="cannot share"):
        TenantFleet(
            d, {**policies, "formation-b": _make_policy(1, hidden=(4, 4))}
        )


# ---------------------------------------------------------------------------
# Per-lane admission
# ---------------------------------------------------------------------------


def test_admission_is_per_lane():
    """Fill lane A's admission queue; lane A's next request is rejected
    with a lane-A Retry-After while lane B is still admitted."""
    d = TenantDirectory(
        [
            TenantSpec(model_id="lane-a", hidden=HIDDEN),
            TenantSpec(model_id="lane-b", hidden=HIDDEN),
        ]
    )
    fleet = TenantFleet(
        d,
        {"lane-a": _make_policy(0), "lane-b": _make_policy(0)},
        num_replicas=1,
        buckets=(1,),
        window_ms=0.0,
        tenant_max_queue=1,
        probe_interval_s=60.0,
    )
    fleet.warmup()
    (replica,) = fleet.replicas
    orig = replica.engine.act

    def slow_act(*args, **kwargs):
        time.sleep(0.3)
        return orig(*args, **kwargs)

    replica.engine.act = slow_act
    with fleet:
        in_flight = fleet.submit(_obs(1, seed=0), model_id="lane-a")
        time.sleep(0.05)  # worker picks it up and blocks in slow_act
        queued = fleet.submit(_obs(1, seed=1), model_id="lane-a")
        with pytest.raises(BackpressureError) as exc:
            fleet.submit(_obs(1, seed=2), model_id="lane-a")
        assert exc.value.retry_after_s > 0.0
        # Lane B's queue is untouched: still admitted, still served.
        other = fleet.submit(_obs(1, seed=3), model_id="lane-b")
        for fut in (in_flight, queued, other):
            assert fut.result(timeout=30).actions.shape == (1, 2)
        snap = fleet.snapshot()
        assert snap["model_lane-a__rejected_total"] == 1.0
        assert snap["model_lane-b__rejected_total"] == 0.0
        # model_id is required on a tenant fleet, and stamped on results.
        with pytest.raises(ValueError, match="model_id"):
            fleet.submit(_obs(1, seed=4))
        res = fleet.submit(_obs(1, seed=5), model_id="lane-b").result(
            timeout=30
        )
        assert res.model_id == "lane-b"


# ---------------------------------------------------------------------------
# The acceptance e2e: isolation + shared executables + mid-storm swap
# ---------------------------------------------------------------------------


def test_tenant_storm_isolation_shared_rungs_and_midstorm_swap(tmp_path):
    """Two same-arch formation lanes + one pursuit lane from ONE fleet:
    a batch storm on formation-a leaves the quiet lanes unrejected and
    step-monotonic; mid-storm, formation-a's coordinator commits a new
    checkpoint (its step advances monotonically) without pausing the
    other lanes; and the compile census shows <= 1 compile per
    (arch, rung) — the executable-sharing receipt."""
    d = _directory(tmp_path)
    fleet = tenant_fleet_from_directory(
        d,
        num_replicas=2,
        buckets=(1, 8),
        watch=False,  # the swap below is driven by hand, mid-storm
    )
    coord = fleet.coordinators["formation-a"]
    swap = {"committed": False}

    def mid_storm():
        _write_ckpt(
            d.get("formation-a").promoted_dir, 150, _make_policy(7)
        )
        swap["committed"] = coord.refresh()

    with fleet:
        report = run_tenant_smoke(
            fleet,
            sizes=(1, 3, 8),
            duration_s=2.0,
            clients_per_lane=2,
            storm_lane="formation-a",
            storm_clients=3,
            mid_storm=mid_storm,
            mid_storm_at_s=0.2,
        )

    assert swap["committed"], "mid-storm swap of formation-a must commit"
    assert coord.last_commit["model_id"] == "formation-a"
    for mid in ("formation-a", "formation-b", "pursuit"):
        assert report[f"model_{mid}__requests_ok"] > 0, report
        assert report[f"model_{mid}__step_monotonic_violations"] == 0.0
    # The quiet lanes never saw the storm: zero rejections, steps flat.
    for mid, step in (("formation-b", 200.0), ("pursuit", 300.0)):
        assert report[f"model_{mid}__rejected"] == 0.0
        assert report[f"model_{mid}__step_min"] == step
        assert report[f"model_{mid}__step_max"] == step
    # The swapped lane's step advanced 100 -> 150, monotonically (the
    # violations pin above covers completion order).
    assert report["model_formation-a__step_min"] == 100.0
    assert report["model_formation-a__step_max"] == 150.0
    assert report["tenant_isolation_p95_ratio"] >= 1.0
    assert np.isfinite(report["tenant_isolation_p95_ratio"])
    # Executable sharing: <= 1 compile per (arch, rung) across BOTH
    # arch groups — two formation lanes rode one set of rungs, and
    # pursuit paid exactly its own.
    shared = report["shared_rung_compiles"]
    assert len(shared) == 4  # 2 arch groups x 2 rungs
    assert all(count == 1 for count in shared.values()), shared
    # The report IS valid bench evidence: the shared gate's tenancy
    # validators accept it as-is.
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        from check_bench_record import check
    finally:
        sys.path.pop(0)
    assert (
        check(dict(report), ["tenant_isolation_p95_ratio"], []) == []
    ), check(dict(report), ["tenant_isolation_p95_ratio"], [])


# ---------------------------------------------------------------------------
# HTTP frontend over a tenant fleet
# ---------------------------------------------------------------------------


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url + "/v1/act",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


def test_frontend_speaks_model_id_end_to_end():
    d = TenantDirectory(
        [
            TenantSpec(model_id="lane-a", hidden=HIDDEN),
            TenantSpec(model_id="lane-b", hidden=HIDDEN),
        ]
    )
    policies = {"lane-a": _make_policy(0), "lane-b": _make_policy(1)}
    fleet = TenantFleet(
        d,
        policies,
        steps={"lane-a": 11, "lane-b": 22},
        num_replicas=2,
        buckets=(1, 8),
    )
    fleet.warmup()
    obs = _obs(3, seed=9)
    with fleet, FleetFrontend(fleet, port=0) as frontend:
        for mid, step in (("lane-a", 11), ("lane-b", 22)):
            body = _post(
                frontend.url, {"obs": obs.tolist(), "model_id": mid}
            )
            ref, _ = policies[mid].predict(obs, deterministic=True)
            np.testing.assert_allclose(
                np.asarray(body["actions"], np.float32), ref,
                rtol=1e-5, atol=1e-6,
            )
            assert body["model_id"] == mid
            assert body["model_step"] == step
        # Distinct lanes really answered with distinct params.
        a, _ = policies["lane-a"].predict(obs, deterministic=True)
        b, _ = policies["lane-b"].predict(obs, deterministic=True)
        assert not np.allclose(a, b)
        # Missing model_id on a tenant fleet -> 400 naming the lanes;
        # unknown lane -> 400 with the did-you-mean hint.
        for payload, needle in (
            ({"obs": obs.tolist()}, "model_id is required"),
            ({"obs": obs.tolist(), "model_id": "lane_a"}, "did you mean"),
        ):
            try:
                _post(frontend.url, payload)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert needle in json.loads(e.read())["error"]
        # Health exposes per-lane steps, each monotonic on its own.
        health = json.loads(
            urllib.request.urlopen(
                frontend.url + "/v1/health", timeout=10
            ).read()
        )
        assert health["model_steps"] == {"lane-a": 11, "lane-b": 22}
        assert health["model_step"] == 22
        # The metrics scrape folds lanes into model-labeled families.
        req = urllib.request.Request(
            frontend.url + "/v1/metrics",
            headers={"Accept": "text/plain"},
        )
        text = urllib.request.urlopen(req, timeout=10).read().decode()
        assert 'marl_model_step{model="lane-a"} 11.0' in text
        assert 'marl_model_step{model="lane-b"} 22.0' in text
