"""SB3 checkpoint importer (compat/sb3_import.py).

The fixture builds a real ``PPO.save``-shaped zip — ``data`` JSON +
``policy.pth`` holding a torch ``state_dict`` with SB3 ActorCriticPolicy
key naming (mlp_extractor.policy_net/value_net Sequential indices,
action_net/value_net heads, log_std) — without needing stable_baselines3
installed. Numeric ground truth is an independent torch forward pass of
the same tanh MLP, so the kernel-transpose mapping is pinned end-to-end.
"""

import json
import sys
import zipfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

torch = pytest.importorskip("torch")

from marl_distributedformation_tpu.compat.sb3_import import (  # noqa: E402
    import_sb3_checkpoint,
    sb3_state_dict_to_flax,
)

OBS_DIM, ACT_DIM, HIDDEN = 8, 2, (64, 64)


def _make_sb3_state_dict(seed: int = 0):
    """Random weights under SB3 ActorCriticPolicy state_dict naming."""
    g = torch.Generator().manual_seed(seed)

    def t(*shape):
        return torch.randn(*shape, generator=g)

    state = {"log_std": t(ACT_DIM)}
    for net in ("policy", "value"):
        dims = (OBS_DIM,) + HIDDEN
        for j in range(len(HIDDEN)):
            # torch.nn.Sequential(Linear, Tanh, Linear, Tanh) indices
            state[f"mlp_extractor.{net}_net.{2 * j}.weight"] = t(
                dims[j + 1], dims[j]
            )
            state[f"mlp_extractor.{net}_net.{2 * j}.bias"] = t(dims[j + 1])
    state["action_net.weight"] = t(ACT_DIM, HIDDEN[-1])
    state["action_net.bias"] = t(ACT_DIM)
    state["value_net.weight"] = t(1, HIDDEN[-1])
    state["value_net.bias"] = t(1)
    return state


def _write_sb3_zip(path: Path, state: dict) -> None:
    import io

    buf = io.BytesIO()
    torch.save(state, buf)
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("data", json.dumps({"policy_class": "MlpPolicy"}))
        zf.writestr("policy.pth", buf.getvalue())
        zf.writestr("_stable_baselines3_version", "2.3.0")


def _torch_forward(state: dict, obs: np.ndarray):
    """Independent ground-truth forward of SB3's separate tanh MLPs."""
    x = torch.as_tensor(obs, dtype=torch.float32)

    def mlp(net: str, x):
        for j in range(len(HIDDEN)):
            w = state[f"mlp_extractor.{net}_net.{2 * j}.weight"]
            b = state[f"mlp_extractor.{net}_net.{2 * j}.bias"]
            x = torch.tanh(x @ w.T + b)
        return x

    mean = mlp("policy", x) @ state["action_net.weight"].T + state[
        "action_net.bias"
    ]
    value = mlp("value", x) @ state["value_net.weight"].T + state[
        "value_net.bias"
    ]
    return mean.numpy(), value.numpy()[..., 0]


def test_forward_parity_after_import(tmp_path):
    """Converted params must reproduce the torch policy's action mean,
    value, and log_std exactly (f32 tolerance)."""
    from marl_distributedformation_tpu.models import MLPActorCritic

    state = _make_sb3_state_dict()
    params, info = sb3_state_dict_to_flax(state)
    assert info == {"obs_dim": OBS_DIM, "act_dim": ACT_DIM, "hidden": HIDDEN}

    obs = np.random.default_rng(1).standard_normal((32, OBS_DIM)).astype(
        np.float32
    )
    mean_j, log_std_j, value_j = MLPActorCritic(act_dim=ACT_DIM).apply(
        params, jnp.asarray(obs)
    )
    mean_t, value_t = _torch_forward(state, obs)
    np.testing.assert_allclose(np.asarray(mean_j), mean_t, atol=1e-5)
    np.testing.assert_allclose(np.asarray(value_j), value_t, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(log_std_j), state["log_std"].numpy(), atol=1e-6
    )


def test_zip_to_playback_roundtrip(tmp_path):
    """SB3 zip -> converted file named for latest_checkpoint discovery ->
    LoadedPolicy.predict serves actions from the imported weights."""
    from marl_distributedformation_tpu.compat import LoadedPolicy
    from marl_distributedformation_tpu.utils import latest_checkpoint

    state = _make_sb3_state_dict(seed=3)
    src = tmp_path / "rl_model_123000_steps.zip"
    _write_sb3_zip(src, state)

    out = import_sb3_checkpoint(src)
    assert out.name == "rl_model_123000_steps.msgpack"
    assert latest_checkpoint(tmp_path) == out

    policy = LoadedPolicy.from_checkpoint(out, act_dim=ACT_DIM)
    obs = np.random.default_rng(2).standard_normal((5, OBS_DIM)).astype(
        np.float32
    )
    actions, _ = policy.predict(obs, deterministic=True)
    mean_t, _ = _torch_forward(state, obs)
    np.testing.assert_allclose(
        actions, np.clip(mean_t, -1.0, 1.0), atol=1e-5
    )


def test_warm_start_resume(tmp_path):
    """A converted (params-only) checkpoint warm-starts Trainer: params
    carried over, fresh optimizer state, timestep counter restored."""
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    state = _make_sb3_state_dict(seed=4)
    src = tmp_path / "rl_model_5000_steps.zip"
    _write_sb3_zip(src, state)
    import_sb3_checkpoint(src)

    trainer = Trainer(
        EnvParams(num_agents=3),
        config=TrainConfig(
            num_formations=2,
            name="sb3_resume",
            log_dir=str(tmp_path),
            resume=True,
            checkpoint=False,
        ),
    )
    assert trainer.num_timesteps == 5000
    got = np.asarray(trainer.train_state.params["params"]["pi_head"]["kernel"])
    np.testing.assert_allclose(
        got, state["action_net.weight"].numpy().T, atol=1e-6
    )
    # Fine-tuning proceeds from the imported weights.
    metrics = trainer.run_iteration()
    assert np.isfinite(float(metrics["loss"]))


def test_shared_trunk_rejected(tmp_path):
    state = _make_sb3_state_dict()
    state["mlp_extractor.shared_net.0.weight"] = torch.zeros(64, OBS_DIM)
    with pytest.raises(ValueError, match="shared-trunk"):
        sb3_state_dict_to_flax(state)


def test_malformed_checkpoints_fail_descriptively():
    """Missing biases (head or hidden) must raise the descriptive
    ValueError path, not a bare KeyError (ADVICE r3)."""
    for victim in ("action_net.bias", "value_net.bias"):
        state = _make_sb3_state_dict()
        del state[victim]
        with pytest.raises(ValueError, match=victim):
            sb3_state_dict_to_flax(state)
    state = _make_sb3_state_dict()
    del state["mlp_extractor.policy_net.0.bias"]
    with pytest.raises(ValueError, match="missing bias"):
        sb3_state_dict_to_flax(state)


def test_cli_rejects_output_collisions(tmp_path, capsys):
    """Two sources mapping to one output path must abort BEFORE any write,
    and --steps with multiple sources is rejected outright."""
    from marl_distributedformation_tpu.compat.sb3_import import main

    a_dir, b_dir = tmp_path / "a", tmp_path / "b"
    a_dir.mkdir(), b_dir.mkdir()
    src_a = a_dir / "rl_model_100_steps.zip"
    src_b = b_dir / "rl_model_100_steps.zip"
    _write_sb3_zip(src_a, _make_sb3_state_dict(seed=5))
    _write_sb3_zip(src_b, _make_sb3_state_dict(seed=6))

    out_dir = tmp_path / "converted"
    with pytest.raises(SystemExit):
        main([str(src_a), str(src_b), "--out-dir", str(out_dir)])
    assert "collision" in capsys.readouterr().err
    assert not list(out_dir.glob("*.msgpack"))  # nothing written

    with pytest.raises(SystemExit):
        main([str(src_a), str(src_b), "--steps", "7"])
    assert "--steps with multiple sources" in capsys.readouterr().err


def test_export_roundtrip(tmp_path):
    """Framework checkpoint -> SB3-named state_dict -> re-import yields
    bit-identical params (the two mappings are exact inverses)."""
    import jax

    from flax import serialization
    from marl_distributedformation_tpu.compat.sb3_import import (
        export_sb3_state_dict,
        _load_policy_state_dict,
    )
    from marl_distributedformation_tpu.models import MLPActorCritic

    model = MLPActorCritic(act_dim=ACT_DIM)
    params = model.init(
        jax.random.PRNGKey(9), np.zeros((1, OBS_DIM), np.float32)
    )
    ckpt = tmp_path / "rl_model_42_steps.msgpack"
    ckpt.write_bytes(
        serialization.msgpack_serialize(
            {"policy": "MLPActorCritic", "params": params,
             "num_timesteps": 42}
        )
    )
    out = export_sb3_state_dict(ckpt)
    assert out.name == "rl_model_42_steps.sb3.pth"

    reimported, info = sb3_state_dict_to_flax(_load_policy_state_dict(out))
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(reimported))
    for path, leaf in flat_a:
        np.testing.assert_array_equal(
            np.asarray(leaf), np.asarray(flat_b[path]), err_msg=str(path)
        )
    assert info["obs_dim"] == OBS_DIM


def test_export_rejects_non_mlp(tmp_path):
    from flax import serialization
    from marl_distributedformation_tpu.compat.sb3_import import (
        export_sb3_state_dict,
    )

    ckpt = tmp_path / "rl_model_1_steps.msgpack"
    ckpt.write_bytes(
        serialization.msgpack_serialize(
            {"policy": "GNNActorCritic", "params": {"params": {}}}
        )
    )
    with pytest.raises(ValueError, match="no SB3 equivalent"):
        export_sb3_state_dict(ckpt)


def test_missing_policy_pth_rejected(tmp_path):
    bad = tmp_path / "rl_model_1_steps.zip"
    with zipfile.ZipFile(bad, "w") as zf:
        zf.writestr("data", "{}")
    with pytest.raises(ValueError, match="policy.pth"):
        import_sb3_checkpoint(bad)
