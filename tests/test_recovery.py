"""Self-healing train lane (train/recovery.py, docs/recovery.md).

The acceptance pins (ISSUE 15): healthy runs are BITWISE identical
health ON vs OFF (host-loop and fused) with budget-1 compile receipts
holding; the in-program skip guard contains a single poisoned iteration
mid-chunk; a NaN bomb mid-fused-run is detected within one chunk drain,
rolls back to last-good, and finishes with finite params while no
non-finite checkpoint ever becomes visible to discovery; the
post-rollback retry stream is a bit-exact pure function of (checkpoint,
recovery index); recovery.jsonl round-trips its schema; and both sweep
drivers carry the health flags through their drain seams.
"""

import shutil

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training.train_state import TrainState

# Bitwise PRNG-stream comparisons need partitionable threefry forced
# before any key math (see PR 3's note in CHANGES.md).
from marl_distributedformation_tpu import jax_compat  # noqa: F401
from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.chaos import (
    FaultSchedule,
    FaultSpec,
    check_finite_checkpoints,
    check_recovery_log,
    get_fault_plane,
)
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.train import (
    HealthConfig,
    RecoveryConfig,
    RecoveryLadder,
    SweepTrainer,
    TrainConfig,
    Trainer,
    fold_recovery_key,
    make_fused_chunk,
    make_health_iteration,
    read_recovery_log,
)
from marl_distributedformation_tpu.train.recovery import (
    HEALTH_ALL,
    scale_injected_lr,
)
from marl_distributedformation_tpu.utils import (
    msgpack_restore_file,
    prune_checkpoints,
)

PPO = PPOConfig(n_steps=4, batch_size=24, n_epochs=2)


def make_trainer(tmp_path, name="run", **overrides):
    defaults = dict(
        num_formations=4,
        checkpoint=False,
        seed=0,
        name=name,
        log_dir=str(tmp_path / name),
        log_interval=1,
    )
    defaults.update(overrides)
    return Trainer(
        EnvParams(num_agents=3), ppo=PPO, config=TrainConfig(**defaults)
    )


def assert_params_equal(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def assert_params_finite(params):
    for leaf in jax.tree_util.tree_leaves(jax.device_get(params)):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            assert np.isfinite(arr).all()


@pytest.fixture(autouse=True)
def _clean_plane():
    plane = get_fault_plane()
    plane.reset()
    plane.enabled = False
    yield
    plane.reset()
    plane.enabled = False


# ---------------------------------------------------------------------------
# Bitwise health ON == OFF on healthy runs (the acceptance pin)
# ---------------------------------------------------------------------------


def test_health_on_bitwise_matches_off_host_loop(tmp_path):
    off = make_trainer(tmp_path, "off")
    on = make_trainer(tmp_path, "on", health=True)
    for _ in range(3):
        m_off = jax.device_get(off.run_iteration())
        m_on = jax.device_get(on.run_iteration())
        # Shared metrics bitwise equal too — the word is a side
        # computation, never a perturbation.
        for name, v in m_off.items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(m_on[name])
            )
        assert float(m_on["health_ok"]) == 1.0
        assert float(m_on["health_word"]) == HEALTH_ALL
    assert_params_equal(off.train_state.params, on.train_state.params)


def test_health_on_bitwise_matches_off_fused_budget_one(tmp_path):
    off = make_trainer(tmp_path, "off", fused_chunk=3)
    on = make_trainer(tmp_path, "on", fused_chunk=3, health=True)
    s_off = jax.device_get(off.run_chunk())
    s_on = jax.device_get(on.run_chunk())
    for name, v in s_off.items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(s_on[name]))
    np.testing.assert_array_equal(s_on["health_ok"], np.ones(3, np.float32))
    assert_params_equal(off.train_state.params, on.train_state.params)
    # Budget-1 compile receipt with health ON: the word adds reductions
    # and selects to the ONE program, never a program of its own.
    assert on.retrace_guard.count == 1
    jax.device_get(on.run_chunk())
    assert on.retrace_guard.count == 1


# ---------------------------------------------------------------------------
# The in-program skip guard (unit, on a toy iteration)
# ---------------------------------------------------------------------------


def _toy_state(value=1.0):
    return TrainState.create(
        apply_fn=lambda *a: None,
        params={"w": jnp.full((3,), value, jnp.float32)},
        tx=optax.sgd(0.0),
    )


def test_skip_guard_contains_single_poisoned_iteration_mid_chunk():
    """Iteration x==2 of a 5-chunk returns NaN params; the guard must
    carry the pre-iteration state through it and the other four
    iterations must land exactly — final w == 1 + 4, flags 1,1,0,1,1."""

    def toy_iteration(ts, env, obs, key, x):
        poisoned = x == 2
        w = ts.params["w"]
        new_w = jnp.where(poisoned, w * jnp.float32(float("nan")), w + 1.0)
        new_ts = ts.replace(params={"w": new_w}, step=ts.step + 1)
        key = jax.random.fold_in(key, 1)
        metrics = {
            "loss": new_w.sum(),
            "grad_norm": jnp.float32(1.0),
        }
        return new_ts, env + 1, obs, key, metrics

    fused = make_fused_chunk(
        make_health_iteration(toy_iteration, HealthConfig()), 5
    )
    ts, env, obs, key = (
        _toy_state(),
        jnp.int32(0),
        jnp.zeros((2,)),
        jax.random.PRNGKey(0),
    )
    out_ts, out_env, _, _, stacked = jax.jit(fused)(
        ts, env, obs, key, jnp.arange(5)
    )
    np.testing.assert_array_equal(
        np.asarray(stacked["health_ok"]),
        np.asarray([1.0, 1.0, 0.0, 1.0, 1.0], np.float32),
    )
    # 4 healthy +1 steps; the poisoned one applied the identity update.
    np.testing.assert_array_equal(
        np.asarray(out_ts.params["w"]), np.full((3,), 5.0, np.float32)
    )
    # The whole carry reverts on a flagged iteration (env counter too),
    # and TrainState.step only advances on committed updates.
    assert int(out_env) == 4
    assert int(out_ts.step) == 4


def test_health_word_decodes_failure_modes():
    """Each failure mode clears exactly its bits: NaN loss, finite-but-
    unbounded grad norm, param-drift blowup."""

    def make_toy(loss_value, grad_value, scale):
        def toy(ts, env, obs, key):
            new_w = ts.params["w"] * jnp.float32(scale)
            new_ts = ts.replace(params={"w": new_w})
            metrics = {
                "loss": jnp.float32(loss_value),
                "grad_norm": jnp.float32(grad_value),
            }
            return new_ts, env, obs, key, metrics

        return toy

    def run(toy):
        wrapped = make_health_iteration(toy, HealthConfig())
        _, _, _, _, m = jax.jit(wrapped)(
            _toy_state(),
            jnp.int32(0),
            jnp.zeros((2,)),
            jax.random.PRNGKey(0),
        )
        return int(m["health_word"]), float(m["health_ok"])

    assert run(make_toy(1.0, 1.0, 1.0)) == (15, 1.0)
    # NaN loss: loss bit clear (grad/drift fine).
    assert run(make_toy(float("nan"), 1.0, 1.0)) == (14, 0.0)
    # Finite-but-unbounded grad norm: only the bounded bit clears.
    assert run(make_toy(1.0, 1.0e9, 1.0)) == (11, 0.0)
    # Param blowup: drift bit clears.
    assert run(make_toy(1.0, 1.0, 1.0e9)) == (7, 0.0)
    # NaN params: drift clears via isfinite(p_new).
    assert run(make_toy(1.0, 1.0, float("nan"))) == (7, 0.0)


# ---------------------------------------------------------------------------
# The e2e: NaN bomb -> detect within one drain -> rollback -> finite finish
# ---------------------------------------------------------------------------

PER_ITER = 4 * 4 * 3  # n_steps * M * N


def _bomb_run(tmp_path, name, at_hit=4, iterations=12, **overrides):
    cfg = dict(
        checkpoint=True,
        save_freq=4,  # two chunks' vec-steps >= save_freq: save per chunk
        fused_chunk=2,
        total_timesteps=iterations * PER_ITER,
        health=True,
        recovery=True,
        recovery_breach_iters=2,
        log_interval=1000,  # quiet
    )
    cfg.update(overrides)
    trainer = make_trainer(tmp_path, name, **cfg)
    plane = get_fault_plane()
    plane.arm(
        FaultSchedule([FaultSpec("train.carry_poison", "raise", at_hit)])
    )
    plane.enabled = True
    trainer.train()
    plane.enabled = False
    return trainer


def test_nan_bomb_rollback_finite_finish_e2e(tmp_path):
    trainer = _bomb_run(tmp_path, "bomb")
    log_dir = tmp_path / "bomb"
    assert not trainer.halted
    assert trainer.num_timesteps == 12 * PER_ITER  # full budget trained
    assert_params_finite(trainer.train_state.params)
    ladder = trainer.recovery_ladder
    assert ladder.recoveries == 1
    assert ladder.breaches == 1
    # Budget-1 receipts held through poison + rollback.
    assert trainer.retrace_guard.count == 1
    events = read_recovery_log(log_dir / "recovery.jsonl")
    kinds = [e["event"] for e in events]
    assert kinds == ["skip", "rollback"]
    skip, rollback = events
    # Detection within ONE chunk drain: the bomb poisons dispatch 4
    # (iterations 6-7 with chunk=2); its drain logs the skip at
    # first_iteration 6 and the rollback lands while the NEXT chunk is
    # in flight.
    assert skip["iteration"] == 6
    assert skip["skipped"] == 2
    assert rollback["iteration"] - skip["iteration"] == 2
    assert rollback["mttr_s"] > 0.0
    # Zero non-finite checkpoints ever visible to discovery.
    assert check_finite_checkpoints(log_dir) == []
    assert check_recovery_log(
        log_dir / "recovery.jsonl", max_rollbacks=3, mttr_bound_s=60.0
    ) == []
    # The poisoned chunk's save was gated/skipped, never published.
    for p in log_dir.glob("rl_model_*.msgpack"):
        tree = msgpack_restore_file(p)
        for leaf in jax.tree_util.tree_leaves(tree["params"]):
            assert np.isfinite(np.asarray(leaf)).all(), p


def test_rollback_retry_is_bit_exact_resume(tmp_path):
    """The post-rollback stream is a pure function of (last-good
    checkpoint, recovery index): a fresh trainer resumed from that
    checkpoint with the same folded key reproduces run A's post-bomb
    trajectory bitwise."""
    a = _bomb_run(tmp_path, "a")
    events = read_recovery_log(tmp_path / "a" / "recovery.jsonl")
    rollback = [e for e in events if e["event"] == "rollback"][0]
    assert rollback["checkpoint"] is not None
    # Run B: a COPY of only the rollback target, resumed cold.
    b_dir = tmp_path / "b"
    b_dir.mkdir()
    src = rollback["checkpoint"]
    shutil.copyfile(src, b_dir / src.split("/")[-1])
    b = make_trainer(
        tmp_path,
        "b",
        checkpoint=False,
        resume=True,
        fused_chunk=2,
        total_timesteps=12 * PER_ITER,
        health=True,
        log_interval=1000,
    )
    assert b.num_timesteps == rollback["to_step"]
    # The manual spelling of what the ladder did: recovery #1's fold.
    b.key = fold_recovery_key(b.key, 1)
    b.train()
    assert b.num_timesteps == a.num_timesteps
    assert_params_equal(a.train_state.params, b.train_state.params)


def test_grad_bomb_quarantines_poisoned_rollback_target(tmp_path):
    """A FINITE 1e18 bomb beats the non-finite write gate into one
    checkpoint (detection lags a chunk); the ladder must quarantine
    that file when the first rollback re-diverges, walk further back,
    and still finish finite without burning the budget."""
    trainer = make_trainer(
        tmp_path,
        "gb",
        checkpoint=True,
        save_freq=4,
        fused_chunk=2,
        total_timesteps=14 * PER_ITER,
        health=True,
        recovery=True,
        recovery_breach_iters=2,
        recovery_max_rollbacks=6,
        log_interval=1000,
    )
    plane = get_fault_plane()
    plane.arm(FaultSchedule([FaultSpec("train.grad_bomb", "raise", 4)]))
    plane.enabled = True
    trainer.train()
    plane.enabled = False
    assert not trainer.halted
    assert_params_finite(trainer.train_state.params)
    ladder = trainer.recovery_ladder
    # Rollback 1 restores the poisoned-but-finite file; rollback 2
    # quarantines it and lands on a clean one; probation keeps the
    # suspect window from minting fresh poisoned checkpoints.
    assert ladder.recoveries == 2
    quarantined = list((tmp_path / "gb").glob("*.quarantined"))
    assert len(quarantined) == 1
    assert check_finite_checkpoints(tmp_path / "gb") == []


def test_host_loop_bomb_rollback_finite_finish(tmp_path):
    """The HOST-LOOP driver's ladder integration: flags observed at the
    log sync, rollback restores, run finishes finite."""
    trainer = make_trainer(
        tmp_path,
        "hl",
        checkpoint=True,
        save_freq=4,
        total_timesteps=12 * PER_ITER,
        health=True,
        recovery=True,
        recovery_breach_iters=2,
        log_interval=1,
    )
    plane = get_fault_plane()
    plane.arm(
        FaultSchedule([FaultSpec("train.carry_poison", "raise", 4)])
    )
    plane.enabled = True
    trainer.train()
    plane.enabled = False
    assert not trainer.halted
    assert trainer.num_timesteps == 12 * PER_ITER
    assert_params_finite(trainer.train_state.params)
    assert trainer.recovery_ladder.recoveries == 1
    assert check_finite_checkpoints(tmp_path / "hl") == []


def test_host_loop_unobserved_tail_poison_still_ends_finite(tmp_path):
    """A bomb the host loop never OBSERVES (log_interval past the run,
    save cadence never reached) must still end on finite params — the
    run-end guarantee, host-loop flavor — and the suspect final save
    must not publish the poison."""
    trainer = make_trainer(
        tmp_path,
        "tail",
        checkpoint=True,
        save_freq=10_000,  # no mid-run saves, no save-cadence observe
        total_timesteps=8 * PER_ITER,
        health=True,
        recovery=True,
        recovery_breach_iters=2,
        log_interval=1000,  # no log-cadence observe either
    )
    plane = get_fault_plane()
    plane.arm(
        FaultSchedule([FaultSpec("train.carry_poison", "raise", 3)])
    )
    plane.enabled = True
    trainer.train()
    plane.enabled = False
    assert_params_finite(trainer.train_state.params)
    # The terminal restore counts as a rollback (the guarantee may
    # exceed the retry budget by one) and no poisoned file is visible.
    assert trainer.recovery_ladder.recoveries == 1
    assert check_finite_checkpoints(tmp_path / "tail") == []


def test_recovery_log_rotates_per_process(tmp_path):
    first = RecoveryLadder(RecoveryConfig(), tmp_path)
    first.observe([0.0] * 3, None, 0)
    assert len(read_recovery_log(tmp_path / "recovery.jsonl")) == 1
    # A second ladder (a resumed run) starts a FRESH file; the old
    # history rotates aside so the per-run validator semantics hold.
    second = RecoveryLadder(RecoveryConfig(), tmp_path)
    assert read_recovery_log(tmp_path / "recovery.jsonl") == []
    assert list(tmp_path.glob("recovery.jsonl.*"))
    second.observe([0.0] * 3, None, 0)
    assert check_recovery_log(tmp_path / "recovery.jsonl") == []


def test_halt_after_rollback_budget_exhausted(tmp_path):
    trainer = _bomb_run(
        tmp_path, "halt", recovery_max_rollbacks=0, iterations=12
    )
    assert trainer.halted
    assert trainer.recovery_ladder.halted
    # Halted short of the budget, ON finite params (restored).
    assert trainer.num_timesteps < 12 * PER_ITER
    assert_params_finite(trainer.train_state.params)
    events = read_recovery_log(tmp_path / "halt" / "recovery.jsonl")
    assert events[-1]["event"] == "halt"
    assert check_recovery_log(tmp_path / "halt" / "recovery.jsonl") == []


def test_lr_backoff_applies_to_injected_rate(tmp_path):
    trainer = _bomb_run(
        tmp_path, "lr", recovery_lr_backoff=0.5, iterations=12
    )
    assert trainer.recovery_ladder.recoveries == 1

    rates = []

    def visit(path, leaf):
        if any(
            getattr(e, "key", getattr(e, "name", None)) == "learning_rate"
            for e in path
        ):
            rates.append(np.asarray(leaf))
        return leaf

    jax.tree_util.tree_map_with_path(visit, trainer.train_state.opt_state)
    assert rates, "recovery_lr_backoff != 1.0 must inject the rate"
    np.testing.assert_allclose(
        float(rates[0]), 0.5 * PPO.learning_rate, rtol=1e-6
    )
    events = read_recovery_log(tmp_path / "lr" / "recovery.jsonl")
    rollback = [e for e in events if e["event"] == "rollback"][0]
    assert rollback["lr_scale"] == 0.5


def test_scale_injected_lr_unit():
    injected = PPO.make_optimizer(inject_lr=True)
    state = injected.init({"w": jnp.ones(3)})
    scaled = scale_injected_lr(state, 0.25)
    assert scaled is not None
    found = []
    jax.tree_util.tree_map_with_path(
        lambda p, leaf: found.append(np.asarray(leaf))
        if any(
            getattr(e, "key", getattr(e, "name", None)) == "learning_rate"
            for e in p
        )
        else None,
        scaled,
    )
    np.testing.assert_allclose(
        float(found[0]), 0.25 * PPO.learning_rate, rtol=1e-6
    )
    # A plain (baked-in lr) opt state has nothing to scale.
    plain = PPO.make_optimizer().init({"w": jnp.ones(3)})
    assert scale_injected_lr(plain, 0.25) is None


def test_fold_recovery_key_streams_are_distinct():
    key = jax.random.PRNGKey(7)
    streams = {
        tuple(np.asarray(jax.random.key_data(k)).tolist())
        for k in (
            key,
            fold_recovery_key(key, 1),
            fold_recovery_key(key, 2),
            fold_recovery_key(key, 3),
        )
    }
    assert len(streams) == 4


# ---------------------------------------------------------------------------
# recovery.jsonl schema round-trip
# ---------------------------------------------------------------------------


def test_recovery_jsonl_schema_round_trip(tmp_path):
    ladder = RecoveryLadder(
        RecoveryConfig(breach_iters=2, max_rollbacks=1), tmp_path
    )
    assert ladder.observe([1.0, 1.0], [15.0, 15.0], 0) == "ok"
    assert ladder.observe([1.0, 0.0], [15.0, 6.0], 2) == "ok"  # 1 skip
    assert ladder.observe([0.0, 0.0], [0.0, 0.0], 4) == "rollback"
    ladder.note_rollback(
        to_step=120, path=str(tmp_path / "x.msgpack"), mttr_s=0.05,
        iteration=6,
    )
    assert ladder.suspect  # probation until a healthy chunk
    assert ladder.observe([1.0, 1.0], [15.0, 15.0], 6) == "ok"
    assert not ladder.suspect
    assert ladder.observe([0.0, 0.0], [0.0, 0.0], 8) == "halt"
    ladder.note_halt(10, "budget exhausted")
    assert ladder.observe([0.0, 0.0], None, 12) == "halt"  # latched
    events = read_recovery_log(tmp_path / "recovery.jsonl")
    assert [e["event"] for e in events] == [
        "skip", "skip", "rollback", "skip", "halt",
    ]
    assert events[1]["health_word_min"] == 0
    assert events[2]["recoveries"] == 1
    # 1 + 2 + 2 skips counted; the post-halt observation is latched
    # out (the ladder is terminal, nothing more accumulates).
    assert ladder.skipped_total == 5
    assert check_recovery_log(tmp_path / "recovery.jsonl") == []
    # The reader REJECTS schema drift, line-addressed.
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"time": 1.0, "event": "rollback", "iteration": 0}\n')
    with pytest.raises(ValueError, match="missing required"):
        read_recovery_log(bad)
    bad.write_text('{"time": 1.0, "event": "explode"}\n')
    with pytest.raises(ValueError, match="unknown recovery event"):
        read_recovery_log(bad)
    bad.write_text("not json\n")
    with pytest.raises(ValueError, match="unparseable"):
        read_recovery_log(bad)


# ---------------------------------------------------------------------------
# The non-finite write gate + retention ring
# ---------------------------------------------------------------------------


def test_nonfinite_checkpoint_write_gate(tmp_path):
    from marl_distributedformation_tpu.utils import AsyncCheckpointWriter

    trainer = make_trainer(tmp_path, "gate", checkpoint=True)
    trainer._poison_carry(float("nan"))
    assert trainer.save() is None  # gate refused; audited, not raised
    assert list((tmp_path / "gate").glob("rl_model_*.msgpack")) == []
    # Async path: skip-with-audit, never a dead run.
    writer = AsyncCheckpointWriter()
    trainer.save_async(writer)
    writer.close()  # must NOT raise
    assert writer.writes_skipped == 1
    assert list((tmp_path / "gate").glob("rl_model_*.msgpack")) == []
    from marl_distributedformation_tpu.obs import get_registry

    assert (
        get_registry().snapshot().get("checkpoint_nonfinite_skipped_total", 0)
        >= 2
    )


def test_retention_ring_prunes_and_protects(tmp_path):
    d = tmp_path / "ring"
    d.mkdir()
    for step in (100, 200, 300, 400, 500):
        (d / f"rl_model_{step}_steps.msgpack").write_bytes(b"x")
    (d / "rl_model_50_steps.msgpack.quarantined").write_bytes(b"x")
    (d / "sweep_state_100_steps.msgpack").write_bytes(b"x")
    (d / "recovery.jsonl").write_text("")
    pruned = prune_checkpoints(
        d, 2, protect=[d / "rl_model_100_steps.msgpack"]
    )
    assert sorted(p.name for p in pruned) == [
        "rl_model_200_steps.msgpack",
        "rl_model_300_steps.msgpack",
    ]
    remaining = sorted(p.name for p in d.iterdir())
    # Newest 2 kept, the protected last-good target survives despite
    # being the OLDEST, quarantine evidence + sweep anchors + audit
    # logs untouched.
    assert set(remaining) == {
        "recovery.jsonl",
        "rl_model_100_steps.msgpack",
        "rl_model_400_steps.msgpack",
        "rl_model_500_steps.msgpack",
        "rl_model_50_steps.msgpack.quarantined",
        "sweep_state_100_steps.msgpack",
    }
    assert prune_checkpoints(d, 0) == []  # 0 = unbounded, no-op


def test_trainer_retention_ring_end_to_end(tmp_path):
    trainer = make_trainer(
        tmp_path,
        "ring",
        checkpoint=True,
        save_freq=4,
        fused_chunk=2,
        total_timesteps=12 * PER_ITER,
        keep_last_n=3,
        log_interval=1000,
    )
    trainer.train()
    ckpts = sorted((tmp_path / "ring").glob("rl_model_*.msgpack"))
    assert len(ckpts) == 3
    # The newest survived (the final save).
    steps = sorted(
        int(p.name.split("_")[2]) for p in ckpts
    )
    assert steps[-1] == trainer.num_timesteps


# ---------------------------------------------------------------------------
# Sweep-driver drain-seam pins
# ---------------------------------------------------------------------------


def test_sweep_drain_seam_health_pins(tmp_path):
    def sweep(name, health):
        return SweepTrainer(
            EnvParams(num_agents=3),
            ppo=PPO,
            config=TrainConfig(
                num_formations=4,
                checkpoint=False,
                seed=0,
                name=name,
                log_dir=str(tmp_path / name),
                fused_chunk=2,
                health=health,
            ),
            num_seeds=2,
        )

    off = sweep("s_off", False)
    on = sweep("s_on", True)
    s_off = jax.device_get(off.run_chunk())
    s_on = jax.device_get(on.run_chunk())
    # Per-member flags stacked (chunk, members) ride the drain.
    assert s_on["health_ok"].shape == (2, 2)
    np.testing.assert_array_equal(
        s_on["health_ok"], np.ones((2, 2), np.float32)
    )
    for name, v in s_off.items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(s_on[name]))
    assert_params_equal(off.train_state.params, on.train_state.params)
    # The drain seam consumes them without touching the aggregate
    # contract (population_aggregate means the flags like any metric).
    from marl_distributedformation_tpu.obs import get_registry

    before = get_registry().snapshot().get(
        "train_skipped_updates_total", 0
    )
    on._drain_chunk(_NullLogger(), _NullMeter(), on.run_chunk(), 2, 0)
    after = get_registry().snapshot().get("train_skipped_updates_total", 0)
    assert after == before  # healthy chunk: zero skips recorded


def test_hetero_sweep_health_flags(tmp_path):
    from marl_distributedformation_tpu.train import (
        Curriculum,
        CurriculumStage,
        HeteroSweepTrainer,
    )

    def hs(name, health):
        t = HeteroSweepTrainer(
            curriculum=Curriculum(
                stages=(CurriculumStage(rollouts=2, agent_counts=(3,)),)
            ),
            env_params=EnvParams(num_agents=3),
            ppo=PPO,
            config=TrainConfig(
                num_formations=4,
                checkpoint=False,
                seed=0,
                name=name,
                log_dir=str(tmp_path / name),
                fused_chunk=2,
                health=health,
            ),
            num_seeds=2,
        )
        t.start_stage(t.curriculum.stages[0])
        return t

    off = hs("h_off", False)
    on = hs("h_on", True)
    s_off = jax.device_get(off.run_chunk())
    s_on = jax.device_get(on.run_chunk())
    assert s_on["health_ok"].shape == (2, 2)
    np.testing.assert_array_equal(
        s_on["health_ok"], np.ones((2, 2), np.float32)
    )
    for name, v in s_off.items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(s_on[name]))
    assert_params_equal(off.train_state.params, on.train_state.params)


class _NullLogger:
    def log(self, *a, **k):
        pass

    def close(self):
        pass


class _NullMeter:
    def tick(self, *a):
        pass

    def rate(self):
        return 0.0
