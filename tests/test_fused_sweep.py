"""Population-scale Anakin: fused-scan sweeps (ISSUE 6 acceptance).

The contract: a ``fused_chunk`` population sweep is BITWISE-identical to
the host-loop sweep at the same seed/config — params AND every
per-member per-iteration metric — for the plain seed sweep, the
lr-hyperparameter sweep, and the hetero curriculum sweep (including
chunks clipped at a stage change); the fused program compiles exactly
once per config (budget-1 RetraceGuard); resume from a chunk-boundary
``sweep_state`` matches an uninterrupted run bit-exactly; the async
population checkpoint writes the same bytes the synchronous save would;
and ``profile=true`` composes with fused mode (trace captured, zero
extra compiles) instead of fail-fasting.
"""

import json
import pathlib

import jax
import numpy as np
import pytest

# Bitwise PRNG-stream comparisons need partitionable threefry forced
# before any key math (see PR 3's note in CHANGES.md).
from marl_distributedformation_tpu import jax_compat  # noqa: F401
from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.train import (
    Curriculum,
    CurriculumStage,
    HeteroSweepTrainer,
    SweepTrainer,
    TrainConfig,
)
from marl_distributedformation_tpu.utils import AsyncCheckpointWriter

PPO = PPOConfig(n_steps=4, batch_size=24, n_epochs=2)
HPPO = PPOConfig(n_steps=4, batch_size=16, n_epochs=2)
CURR = Curriculum(
    stages=(
        CurriculumStage(rollouts=2, agent_counts=(3,)),
        CurriculumStage(rollouts=3, agent_counts=(3, 5), num_obstacles=1),
    )
)
PER_ITER = PPO.n_steps * 4 * 3  # n_steps * M * N agent-transitions


def make_sweep(log_dir, **overrides):
    defaults = dict(
        num_formations=4,
        seed=0,
        checkpoint=False,
        name="fsweep",
        log_dir=str(log_dir),
    )
    lrs = overrides.pop("learning_rates", None)
    num_seeds = overrides.pop("num_seeds", 2)
    defaults.update(overrides)
    return SweepTrainer(
        EnvParams(num_agents=3),
        ppo=PPO,
        config=TrainConfig(**defaults),
        num_seeds=num_seeds,
        learning_rates=lrs,
    )


def make_hetero(log_dir, **overrides):
    defaults = dict(
        num_formations=4,
        seed=0,
        checkpoint=False,
        name="hfsweep",
        log_dir=str(log_dir),
    )
    defaults.update(overrides)
    return HeteroSweepTrainer(
        curriculum=CURR,
        env_params=EnvParams(num_agents=3),
        ppo=HPPO,
        config=TrainConfig(**defaults),
        num_seeds=2,
    )


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Bitwise parity: fused population scan == host-loop sweep
# ---------------------------------------------------------------------------


def test_fused_sweep_bitwise_matches_host_loop(tmp_path):
    """Two fused chunks of 2 == four host-loop sweep iterations: params
    and every per-member per-iteration metric, bit for bit."""
    host = make_sweep(tmp_path / "host")
    fused = make_sweep(tmp_path / "fused", fused_chunk=2)
    per_iter = [jax.device_get(host.run_iteration()) for _ in range(4)]
    for chunk in range(2):
        stacked = jax.device_get(fused.run_chunk())
        for name, values in stacked.items():
            for i in range(2):
                np.testing.assert_array_equal(
                    np.asarray(values[i]),
                    np.asarray(per_iter[2 * chunk + i][name]),
                    err_msg=(
                        f"metric {name!r} diverges at chunk {chunk} "
                        f"iteration {i}"
                    ),
                )
    assert host.num_timesteps == fused.num_timesteps
    _leaves_equal(host.train_state.params, fused.train_state.params)
    _leaves_equal(host.key, fused.key)


def test_fused_lr_sweep_bitwise_matches_host_loop(tmp_path):
    """Per-member injected learning rates ride the scan carry (optimizer
    STATE) — the lr sweep fuses bitwise too."""
    lrs = [1e-3, 3e-3]
    host = make_sweep(tmp_path / "host", learning_rates=lrs)
    fused = make_sweep(
        tmp_path / "fused", learning_rates=lrs, fused_chunk=2
    )
    for _ in range(2):
        host.run_iteration()
    fused.run_chunk()
    _leaves_equal(host.train_state.params, fused.train_state.params)
    _leaves_equal(host.train_state.opt_state, fused.train_state.opt_state)


def test_fused_sweep_compiles_exactly_once_across_chunks(tmp_path):
    """Three chunks = ONE compile of the fused population program
    (guard_retraces=1 would raise on a retrace; the count is the receipt
    bench.py records per rung)."""
    fused = make_sweep(tmp_path, fused_chunk=2, guard_retraces=1)
    for _ in range(3):
        fused.run_chunk()
    assert fused.retrace_guard.count == 1


def test_run_iteration_refuses_fused_mode(tmp_path):
    fused = make_sweep(tmp_path / "f", fused_chunk=2)
    with pytest.raises(AssertionError, match="run_chunk"):
        fused.run_iteration()
    host = make_sweep(tmp_path / "h")
    with pytest.raises(AssertionError, match="fused_chunk"):
        host.run_chunk()


# ---------------------------------------------------------------------------
# End-to-end: train() with async population checkpoints + resume
# ---------------------------------------------------------------------------


def test_fused_sweep_train_end_to_end_and_resume(tmp_path):
    """4 iterations in 2 fused chunks: per-iteration aggregate records
    land in metrics.jsonl at host-loop step stamps, the background
    writer lands per-member checkpoints + the sweep_state anchor at the
    chunk boundary, and a resume from that boundary ends bit-identical
    to an uninterrupted run (the chunk-aware resume cadence: chunk
    boundary == bit-exact resume boundary)."""
    kw = dict(checkpoint=True, save_freq=10**9, fused_chunk=2)

    full = make_sweep(
        tmp_path / "full", total_timesteps=4 * PER_ITER, **kw
    )
    record = full.train()
    assert full.num_timesteps == 4 * PER_ITER
    assert np.isfinite(record["loss"])
    assert "reward_best" in record and "best_seed" in record
    assert full.retrace_guard.count == 1
    records = [
        json.loads(line)
        for line in (tmp_path / "full" / "metrics.jsonl")
        .read_text()
        .splitlines()
    ]
    assert [r["step"] for r in records] == [
        PER_ITER, 2 * PER_ITER, 3 * PER_ITER, 4 * PER_ITER,
    ]
    # The async writer landed the full artifact set: member checkpoints
    # discoverable by the standard tooling + the population anchor.
    for i in range(2):
        assert list(
            (tmp_path / "full" / f"seed{i}").glob("rl_model_*_steps.msgpack")
        )
    assert (
        tmp_path / "full" / f"sweep_state_{4 * PER_ITER}_steps.msgpack"
    ).exists()
    summary = json.loads(
        (tmp_path / "full" / "sweep_summary.json").read_text()
    )
    assert len(summary["final_reward"]) == 2

    half = make_sweep(
        tmp_path / "part", total_timesteps=2 * PER_ITER, **kw
    )
    half.train()
    resumed = make_sweep(
        tmp_path / "part", total_timesteps=4 * PER_ITER, resume=True, **kw
    )
    assert resumed.num_timesteps == 2 * PER_ITER
    resumed.train()
    for getter in (
        lambda t: t.train_state.params,
        lambda t: t.train_state.opt_state,
        lambda t: t.key,
        lambda t: t.env_state,
        lambda t: t.obs,
    ):
        _leaves_equal(getter(resumed), getter(full))
    s_res = json.loads(
        (tmp_path / "part" / "sweep_summary.json").read_text()
    )
    assert s_res["best_seed"] == summary["best_seed"]
    np.testing.assert_array_equal(
        s_res["final_reward"], summary["final_reward"]
    )


def test_fused_sweep_async_save_matches_sync_save_bytes(tmp_path):
    """save_async writes byte-identical files to the synchronous save —
    member checkpoints AND the sweep_state anchor (the device snapshot +
    writer thread change WHEN the bytes are produced, never WHAT)."""
    a = make_sweep(tmp_path / "a", fused_chunk=2, checkpoint=True)
    b = make_sweep(tmp_path / "b", fused_chunk=2, checkpoint=True)
    a.run_chunk()
    b.run_chunk()
    a.save()
    writer = AsyncCheckpointWriter()
    b.save_async(writer)
    writer.close()
    names = [
        f"sweep_state_{a.num_timesteps}_steps.msgpack",
        f"seed0/rl_model_{a.num_timesteps}_steps.msgpack",
        f"seed1/rl_model_{a.num_timesteps}_steps.msgpack",
    ]
    for name in names:
        sync_bytes = (pathlib.Path(a.log_dir) / name).read_bytes()
        async_bytes = (pathlib.Path(b.log_dir) / name).read_bytes()
        assert sync_bytes == async_bytes, f"{name} drifted sync vs async"


# ---------------------------------------------------------------------------
# Hetero curriculum sweep: fused chunks clip at stage boundaries
# ---------------------------------------------------------------------------


def test_hetero_fused_matches_host_loop_across_stage_change(tmp_path):
    """The 2+3-rollout curriculum under chunk=2 dispatches chunks
    [2][2][1] — a stage change between chunks AND a clipped tail inside
    stage 2. Params, member counters, and the curriculum cursor must
    match the host loop bitwise; the clipped tail costs exactly one
    extra compile (2 distinct scan lengths -> 2 compiles, ever)."""
    host = make_hetero(tmp_path / "host")
    fused = make_hetero(tmp_path / "fused", fused_chunk=2)
    host.train()
    fused.train()
    assert host.completed_rollouts == fused.completed_rollouts == 5
    _leaves_equal(host.train_state.params, fused.train_state.params)
    _leaves_equal(host.key, fused.key)
    np.testing.assert_array_equal(
        host.num_timesteps_members, fused.num_timesteps_members
    )
    assert fused.retrace_guard.count == 2, (
        "chunk lengths {2, 1} must compile once each, never per dispatch"
    )


def test_hetero_fused_resume_from_chunk_boundary(tmp_path):
    """An interrupted fused curriculum block resumed from its
    chunk-boundary sweep_state ends bit-identical to an uninterrupted
    fused run — including a boundary that is also a STAGE boundary (the
    checkpoint must hold the pre-reset key so resume replays the stage
    reset exactly once)."""
    kw = dict(checkpoint=True, save_freq=10**9, fused_chunk=2)
    per_iter_max = HPPO.n_steps * 4 * 3

    full = make_hetero(tmp_path / "full", **kw)
    full.train()

    part = make_hetero(
        tmp_path / "part", total_timesteps=2 * per_iter_max, **kw
    )
    part.train()  # cap lands at rollout 2 == the stage-0/1 boundary
    assert part.completed_rollouts == 2

    resumed = make_hetero(tmp_path / "part", resume=True, **kw)
    assert resumed.completed_rollouts == 2
    resumed.train()
    assert resumed.completed_rollouts == full.completed_rollouts
    for getter in (
        lambda t: t.train_state.params,
        lambda t: t.train_state.opt_state,
        lambda t: t.key,
        lambda t: t.env_state,
        lambda t: t.obs,
    ):
        _leaves_equal(getter(resumed), getter(full))
    np.testing.assert_array_equal(
        resumed.num_timesteps_members, full.num_timesteps_members
    )


# ---------------------------------------------------------------------------
# profile=true composes with fused sweeps (trace captured, no retrace)
# ---------------------------------------------------------------------------


def test_profile_composes_with_fused_sweep(tmp_path):
    """profile=true on a fused sweep captures a chunk-granular trace
    (files land under {log_dir}/profile/) with ZERO extra compiles —
    the combination used to fail-fast."""
    sweep = make_sweep(
        tmp_path,
        fused_chunk=2,
        total_timesteps=4 * PER_ITER,
        profile=True,
        profile_iterations=1,
        guard_retraces=1,
    )
    sweep.train()
    trace_files = list((tmp_path / "profile").rglob("*"))
    assert any(p.is_file() for p in trace_files), (
        f"no profiler trace captured under {tmp_path / 'profile'}"
    )
    assert sweep.retrace_guard.count == 1, (
        "tracing must not retrace the fused program"
    )


# ---------------------------------------------------------------------------
# The burst cadence is retired for sweeps; fail-fasts stay loud
# ---------------------------------------------------------------------------


def test_sweep_burst_cadence_retired(tmp_path):
    with pytest.raises(SystemExit, match="fused_chunk"):
        make_sweep(tmp_path, iters_per_dispatch=2)
    with pytest.raises(SystemExit, match="fused_chunk"):
        make_hetero(tmp_path, iters_per_dispatch=2)
