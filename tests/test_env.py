"""Unit tests for the pure-functional formation environment.

Covers the reference semantics documented in SURVEY.md §2.1 (components
2, 4-7) and the quirk ledger §8 with hand-computed fixtures — the test
strategy the reference lacks (SURVEY.md §4).
"""

import chex
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.env import (
    EnvParams,
    compute_metrics,
    compute_obs,
    compute_reward,
    make_vec_env,
    reset,
    reset_batch,
    step,
    step_batch,
)


@pytest.fixture
def params():
    return EnvParams(num_agents=5)


def test_reset_shapes_and_bounds(params):
    state = reset(jax.random.PRNGKey(0), params)
    chex.assert_shape(state.agents, (5, 2))
    chex.assert_shape(state.goal, (2,))
    chex.assert_shape(state.obstacles, (0, 2))
    assert state.agents.dtype == jnp.float32
    assert int(state.steps) == 0
    # Agents spawn in the bottom 100-px strip (simulate.py:133-135).
    assert (state.agents[:, 0] >= 0).all() and (state.agents[:, 0] <= 400).all()
    assert (state.agents[:, 1] >= 0).all() and (state.agents[:, 1] <= 100).all()
    # Goal keeps a desired_radius margin from every wall (simulate.py:140-143).
    assert 60 <= float(state.goal[0]) <= 400 - 60
    assert 60 <= float(state.goal[1]) <= 600 - 60


def test_reset_deterministic_per_key(params):
    a = reset(jax.random.PRNGKey(7), params)
    b = reset(jax.random.PRNGKey(7), params)
    c = reset(jax.random.PRNGKey(8), params)
    chex.assert_trees_all_equal(a, b)
    assert not np.allclose(np.asarray(a.agents), np.asarray(c.agents))


def test_obstacle_reset_band():
    p = EnvParams(num_agents=4, num_obstacles=16, obstacle_mode="fixed")
    state = reset(jax.random.PRNGKey(3), p)
    chex.assert_shape(state.obstacles, (16, 2))
    ob = np.asarray(state.obstacles)
    assert (ob[:, 0] >= 10).all() and (ob[:, 0] <= 390).all()
    # Middle band: y in [100 + size, 500 - size] (simulate.py:127).
    assert (ob[:, 1] >= 110).all() and (ob[:, 1] <= 490).all()


def test_obs_hand_computed():
    p = EnvParams(num_agents=3)
    agents = jnp.array([[40.0, 60.0], [80.0, 120.0], [200.0, 300.0]])
    goal = jnp.array([240.0, 360.0])
    obs = compute_obs(agents, goal, p)
    chex.assert_shape(obs, (3, 8))
    na = np.asarray(agents) / np.array([400.0, 600.0])
    # Agent 0: prev is agent 2, next is agent 1 (simulate.py:162-167).
    np.testing.assert_allclose(np.asarray(obs[0, :2]), na[0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(obs[0, 2:4]), na[2] - na[0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(obs[0, 4:6]), na[1] - na[0], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(obs[1, 6:8]),
        (np.asarray(goal) - np.asarray(agents[1])) / np.array([400.0, 600.0]),
        rtol=1e-6,
    )


def test_obs_without_goal():
    p = EnvParams(num_agents=4, goal_in_obs=False)
    obs = compute_obs(
        jnp.ones((4, 2)) * 50.0, jnp.array([200.0, 300.0]), p
    )
    chex.assert_shape(obs, (4, 6))


def test_reward_hand_computed():
    """Two agents on a line near the goal; every term computed by hand."""
    p = EnvParams(num_agents=2, share_reward_ratio=0.0)
    # desired_neighbor_dist = 2*60*sin(pi/2) = 120.
    assert np.isclose(p.desired_neighbor_dist, 120.0)
    agents = jnp.array([[200.0, 300.0], [200.0, 400.0]])
    goal = jnp.array([200.0, 300.0])
    oob = jnp.zeros(2, bool)
    in_obs = jnp.zeros(2, bool)
    reward, terms = compute_reward(agents, goal, oob, in_obs, p)
    # Agent 0: dist 0 -> close bonus 10, dist term 0; both neighbor dists are
    # 100 -> diff -20, quadratic penalty 0.01*400 = 4 per side.
    np.testing.assert_allclose(float(reward[0]), 10.0 - 4.0 - 4.0, rtol=1e-5)
    # Agent 1: dist 100 -> not close (strict <), dist term -10, same spacing.
    np.testing.assert_allclose(float(reward[1]), -10.0 - 4.0 - 4.0, rtol=1e-5)
    assert set(terms) == {
        "close_to_goal_reward",
        "reward_dist",
        "reward_right_neighbor",
        "reward_left_neighbor",
    }


def test_reward_linear_when_too_far():
    p = EnvParams(num_agents=2, share_reward_ratio=0.0)
    agents = jnp.array([[0.0, 0.0], [0.0, 200.0]])
    goal = jnp.array([200.0, 300.0])
    reward, _ = compute_reward(
        agents, goal, jnp.zeros(2, bool), jnp.zeros(2, bool), p
    )
    # Spacing 200 vs desired 120 -> linear penalty 0.01*80 = 0.8 per side
    # (simulate.py:204: quadratic only when too close).
    d0 = float(jnp.linalg.norm(agents[0] - goal))
    np.testing.assert_allclose(
        float(reward[0]), -0.1 * d0 - 0.8 - 0.8, rtol=1e-4
    )


def test_reward_mixing_limits():
    agents = jnp.array([[10.0, 10.0], [60.0, 30.0], [300.0, 500.0]])
    goal = jnp.array([200.0, 300.0])
    oob = jnp.zeros(3, bool)
    in_obs = jnp.zeros(3, bool)
    r0, _ = compute_reward(
        agents, goal, oob, in_obs, EnvParams(num_agents=3, share_reward_ratio=0.0)
    )
    rhalf, _ = compute_reward(
        agents, goal, oob, in_obs, EnvParams(num_agents=3, share_reward_ratio=0.5)
    )
    # rho=0.5: own reward fully replaced by the neighbor average
    # (simulate.py:228-229).
    expected = 0.5 * (np.roll(np.asarray(r0), 1) + np.roll(np.asarray(r0), -1))
    np.testing.assert_allclose(np.asarray(rhalf), expected, rtol=1e-5)


def test_out_of_bounds_penalty_and_clip(params):
    state = reset(jax.random.PRNGKey(0), params)
    # Push every agent far left/down out of the box.
    vel = -jnp.ones((5, 2)) * 1000.0
    next_state, tr = step(state, vel, params)
    assert (np.asarray(next_state.agents) >= 0).all()
    # With rho=0.25 mixing, every agent carries the full -100 penalty
    # because all agents are out of bounds simultaneously.
    assert (np.asarray(tr.reward) < -90).all()


def test_obstacle_containment_parity_geometry():
    """Q2: parity mode treats the obstacle point as a lower-left corner of an
    obstacle_size box; fixed mode as the center of a 2*obstacle_size box."""
    from marl_distributedformation_tpu.env.formation import _in_obstacle

    p = EnvParams(num_agents=2, num_obstacles=1)
    obstacles = jnp.array([[200.0, 300.0]])
    # Agent 0 inside [200,210]x[300,310]; agent 1 at the *center-box-only*
    # location (195, 295), inside the rendered box but not the parity box.
    agents = jnp.array([[205.0, 305.0], [195.0, 295.0]])
    flags = _in_obstacle(agents, obstacles, p)
    assert bool(flags[0]) and not bool(flags[1])

    p_fixed = p.replace(obstacle_mode="fixed")
    flags_fixed = _in_obstacle(agents, obstacles, p_fixed)
    # Fixed mode: center box [190,210]x[290,310] contains both agents.
    assert bool(flags_fixed[0]) and bool(flags_fixed[1])

    # The flag feeds a -100 penalty into the reward (simulate.py:215-217).
    r_hit, _ = compute_reward(
        agents,
        jnp.array([205.0, 305.0]),
        jnp.zeros(2, bool),
        flags,
        p.replace(share_reward_ratio=0.0),
    )
    r_clear, _ = compute_reward(
        agents,
        jnp.array([205.0, 305.0]),
        jnp.zeros(2, bool),
        jnp.zeros(2, bool),
        p.replace(share_reward_ratio=0.0),
    )
    np.testing.assert_allclose(
        np.asarray(r_hit - r_clear), [-100.0, 0.0], atol=1e-5
    )


def test_episode_length_strict_parity():
    """Q1: done fires when the pre-increment counter exceeds max_steps,
    so episodes run max_steps + 2 steps (simulate.py:111,231)."""
    p = EnvParams(num_agents=3, max_steps=10)
    state = reset(jax.random.PRNGKey(0), p)

    def body(carry, _):
        st, done_step, i = carry
        st, tr = step(st, jnp.zeros((3, 2)), p)
        done_step = jnp.where(
            (done_step < 0) & tr.done, i, done_step
        )
        return (st, done_step, i + 1), tr.done
    (_, done_step, _), dones = jax.lax.scan(
        body, (state, jnp.int32(-1), jnp.int32(1)), None, length=20
    )
    # 1-based step index at which done first fires: max_steps + 2 = 12.
    assert int(done_step) == 12
    assert int(dones.sum()) == 1  # counter resets with the episode


def test_episode_length_exact_when_not_parity():
    p = EnvParams(num_agents=3, max_steps=10, strict_parity=False)
    state = reset(jax.random.PRNGKey(0), p)
    done_at = None
    for i in range(1, 15):
        state, tr = step(state, jnp.zeros((3, 2)), p)
        if bool(tr.done):
            done_at = i
            break
    assert done_at == 10


def test_goal_termination_flag():
    p = EnvParams(
        num_agents=3, strict_parity=False, goal_termination=True
    )
    state = reset(jax.random.PRNGKey(0), p)
    # Teleport everyone onto the goal via a crafted velocity.
    vel = state.goal[None, :] - state.agents
    _, tr = step(state, vel, p)
    assert bool(tr.done)


def test_auto_reset_returns_next_episode_obs():
    """SB3 VecEnv convention (simulate.py:113-118): on done, the returned
    obs belongs to the next episode while the reward is terminal."""
    p = EnvParams(num_agents=3, max_steps=0, strict_parity=False)
    state = reset(jax.random.PRNGKey(5), p)
    next_state, tr = step(state, jnp.zeros((3, 2)), p)
    assert bool(tr.done)
    assert int(next_state.steps) == 0
    expected_fresh = reset(state.key, p)
    chex.assert_trees_all_close(next_state.agents, expected_fresh.agents)
    np.testing.assert_allclose(
        np.asarray(tr.obs),
        np.asarray(compute_obs(expected_fresh.agents, expected_fresh.goal, p)),
        rtol=1e-6,
    )
    # A new goal was drawn (old goal overwhelmingly unlikely to repeat).
    assert not np.allclose(np.asarray(next_state.goal), np.asarray(state.goal))


def test_metrics_match_numpy():
    p = EnvParams(num_agents=4)
    agents = jnp.array(
        [[10.0, 20.0], [50.0, 80.0], [90.0, 10.0], [200.0, 400.0]]
    )
    goal = jnp.array([100.0, 100.0])
    m = compute_metrics(agents, goal, p)
    a = np.asarray(agents)
    d_goal = np.linalg.norm(a - np.asarray(goal), axis=1)
    d_right = np.linalg.norm(a - np.roll(a, -1, axis=0), axis=1)
    np.testing.assert_allclose(float(m["avg_dist_to_goal"]), d_goal.mean(), rtol=1e-5)
    np.testing.assert_allclose(
        float(m["ave_dist_to_neighbor"]), d_right.mean(), rtol=1e-5
    )
    # torch .std() is the unbiased estimator (ddof=1).
    np.testing.assert_allclose(
        float(m["std_dist_to_neighbor"]), d_right.std(ddof=1), rtol=1e-5
    )


def test_batch_matches_single(params):
    """vmap over formations is semantically the reference's sequential loop
    (vectorized_env.py:71-81)."""
    M = 4
    state = reset_batch(jax.random.PRNGKey(1), params, M)
    vel = jax.random.normal(jax.random.PRNGKey(2), (M, 5, 2))
    batched_state, batched_tr = step_batch(state, vel, params)
    for i in range(M):
        single = jax.tree_util.tree_map(lambda x: x[i], state)
        s_state, s_tr = step(single, vel[i], params)
        chex.assert_trees_all_close(
            jax.tree_util.tree_map(lambda x: x[i], batched_state), s_state,
            rtol=1e-6,
        )
        chex.assert_trees_all_close(
            jax.tree_util.tree_map(lambda x: x[i], batched_tr), s_tr,
            rtol=1e-6,
        )


def test_make_vec_env_contract(params):
    reset_fn, step_fn = make_vec_env(params, num_formations=3)
    state, obs = reset_fn(jax.random.PRNGKey(0))
    chex.assert_shape(obs, (3, 5, 8))
    actions = jnp.clip(
        jax.random.normal(jax.random.PRNGKey(1), (3, 5, 2)), -1, 1
    )
    state2, tr = step_fn(state, actions)
    chex.assert_shape(tr.obs, (3, 5, 8))
    chex.assert_shape(tr.reward, (3, 5))
    chex.assert_shape(tr.done, (3,))
    # max_speed scaling (vectorized_env.py:69-70): displacement = 10 * action
    # wherever no clipping happened.
    moved = np.asarray(state2.agents - state.agents)
    inside = (
        (np.asarray(state2.agents) > 0) & (np.asarray(state2.agents) < [400, 600])
    ).all(axis=-1, keepdims=True)
    np.testing.assert_allclose(
        np.where(inside, moved, 0.0),
        np.where(inside, 10.0 * np.asarray(actions), 0.0),
        atol=1e-4,
    )
