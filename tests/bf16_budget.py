"""bf16-inference divergence budget for the sharded/bf16 serving rungs
(the adam_budget.py methodology applied to the forward pass: an explicit
amplification bound derived from the numerics, not a flat tolerance).

The facts the budget is built from:

1. **Cast rounding.** bfloat16 keeps 8 mantissa bits, so casting an f32
   value to bf16 (round-to-nearest) perturbs it by at most half an ulp:
   ``2**-9`` relative. The engine's bf16 rungs cast exactly two things
   in-program — every float param leaf and the obs buffer — once per
   dispatch; actions return f32 (engine.py ``_build_act``).
2. **No accumulation growth.** XLA accumulates bf16 dot products in
   f32 (the default ``preferred_element_type`` promotion), so a K-term
   contraction contributes ONE rounding of each operand, not a
   ``sqrt(K)``-growing sum-order error. The error budget is therefore
   per-LAYER, not per-multiply-add.
3. **Lipschitz propagation.** The policy head is a tanh-MLP: tanh is
   1-Lipschitz and both weights and activations are O(1) at serving
   scale (actions clip to [-1, 1]), so layer ``i`` forwards its input
   perturbation with gain ~1 and adds its own two cast roundings
   (weights, and the incoming activation re-rounded by the bf16
   multiply). A depth-``D`` stack is bounded by ``(2 D + 1)`` roundings.
4. **Measured headroom.** Observed deterministic-action divergence of
   the bf16 512-rung vs the f32 ladder (default MLPActorCritic, this
   container): ~8e-5 — roughly 100x inside the worst-case bound, the
   cancellation the Lipschitz bound deliberately does not assume.

So the budget for actions is ``atol = (2 * num_layers + 1) * 2**-9``
with ``rtol = 0`` — action components are clipped O(1) quantities, so
an absolute tolerance is the principled unit (same argument as the
Adam budget's ``atol = lr * U``). Deterministic actions only: sampled
actions add a bf16-rounded ``exp(log_std)`` noise scale whose budget
would be dominated by the noise itself, and every parity gate (and the
bench) serves deterministic.
"""

# Half-ulp relative rounding of an f32 -> bf16 cast (8 mantissa bits).
BF16_EPS = 2.0**-9


def bf16_action_atol(num_layers: int) -> float:
    """Action-space budget for a depth-``num_layers`` tanh-MLP served
    in bf16 vs f32: ``2`` cast roundings per layer (weights + incoming
    activation) plus the obs cast, each forwarded at Lipschitz gain ~1.
    Use with ``rtol=0`` — see the module docstring for the derivation.
    """
    return (2 * num_layers + 1) * BF16_EPS
