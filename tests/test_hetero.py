"""Heterogeneous (padded mixed-N) env + curriculum tests.

Core property: a formation with n active agents padded to N_max must match
the homogeneous env at num_agents=n exactly — same obs, rewards, done — for
the active rows, with padding rows inert (zero obs/reward, zero loss weight).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.algo import (
    MinibatchData,
    PPOConfig,
    ppo_loss,
)
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.formation import (
    compute_obs,
    reset,
    step,
)
from marl_distributedformation_tpu.env.hetero import (
    HeteroState,
    agent_mask,
    hetero_compute_obs,
    hetero_reset,
    hetero_reset_batch,
    hetero_step,
    hetero_step_batch,
    make_hetero_vec_env,
    ring_gather_indices,
)
from marl_distributedformation_tpu.models import MLPActorCritic
from marl_distributedformation_tpu.train import (
    Curriculum,
    CurriculumStage,
    HeteroTrainer,
    TrainConfig,
    sample_stage_counts,
)

N_MAX = 8


def make_padded_state(key, n, params_small, params_padded):
    """A hetero state whose first n rows equal a homogeneous reset at N=n."""
    small = reset(key, params_small)
    pad = jnp.zeros((N_MAX - n, 2), jnp.float32) + 7.0
    return small, HeteroState(
        agents=jnp.concatenate([small.agents, pad]),
        goal=small.goal,
        obstacles=jnp.zeros((0, 2), jnp.float32),
        steps=small.steps,
        key=small.key,
        n_agents=jnp.asarray(n, jnp.int32),
        n_obstacles=jnp.asarray(0, jnp.int32),
    )


class TestRingGather:
    def test_matches_roll_when_full(self):
        n = jnp.asarray(N_MAX, jnp.int32)
        prev, nxt = ring_gather_indices(n, N_MAX)
        idx = np.arange(N_MAX)
        np.testing.assert_array_equal(np.asarray(prev), (idx - 1) % N_MAX)
        np.testing.assert_array_equal(np.asarray(nxt), (idx + 1) % N_MAX)

    def test_partial_ring_wraps_at_n(self):
        prev, nxt = ring_gather_indices(jnp.asarray(5, jnp.int32), N_MAX)
        assert int(prev[0]) == 4  # agent 0's prev is agent n-1, not N_max-1
        assert int(nxt[4]) == 0
        # padded slots still index inside [0, n)
        assert int(prev[7]) < 5 and int(nxt[7]) < 5

    def test_mask(self):
        m = agent_mask(jnp.asarray(3, jnp.int32), N_MAX)
        np.testing.assert_array_equal(
            np.asarray(m), [True] * 3 + [False] * 5
        )


class TestPaddedEqualsHomogeneous:
    @pytest.mark.parametrize("n", [2, 5, 8])
    def test_obs_parity(self, n):
        params_n = EnvParams(num_agents=n)
        params_pad = EnvParams(num_agents=N_MAX)
        small, padded = make_padded_state(
            jax.random.PRNGKey(0), n, params_n, params_pad
        )
        obs_small = compute_obs(small.agents, small.goal, params_n)
        obs_pad = hetero_compute_obs(padded, params_pad)
        np.testing.assert_allclose(
            np.asarray(obs_pad[:n]), np.asarray(obs_small), rtol=1e-6
        )
        assert not np.any(np.asarray(obs_pad[n:]))

    @pytest.mark.parametrize("n", [2, 5, 8])
    def test_step_parity(self, n):
        params_n = EnvParams(num_agents=n)
        params_pad = EnvParams(num_agents=N_MAX)
        small, padded = make_padded_state(
            jax.random.PRNGKey(1), n, params_n, params_pad
        )
        vel = jax.random.normal(jax.random.PRNGKey(2), (n, 2)) * 5.0
        vel_pad = jnp.concatenate(
            [vel, jnp.full((N_MAX - n, 2), 123.0)]  # garbage on padded rows
        )
        _, tr_small = step(small, vel, params_n)
        next_pad, tr_pad = hetero_step(padded, vel_pad, params_pad)

        np.testing.assert_allclose(
            np.asarray(tr_pad.reward[:n]),
            np.asarray(tr_small.reward),
            rtol=1e-5,
            atol=1e-5,
        )
        assert not np.any(np.asarray(tr_pad.reward[n:]))
        np.testing.assert_allclose(
            np.asarray(tr_pad.obs[:n]),
            np.asarray(tr_small.obs),
            rtol=1e-5,
            atol=1e-6,
        )
        assert bool(tr_pad.done) == bool(tr_small.done)
        # padded agents must not have moved (zero-velocity mask)
        np.testing.assert_allclose(
            np.asarray(next_pad.agents[n:]), np.asarray(padded.agents[n:])
        )
        # metrics reduce over active agents only
        for k in ("avg_dist_to_goal", "ave_dist_to_neighbor"):
            np.testing.assert_allclose(
                float(tr_pad.metrics[k]),
                float(tr_small.metrics[k]),
                rtol=1e-5,
            )

    def test_dynamic_spacing_target(self):
        """The spacing penalty must use 2*R*sin(pi/n) for the formation's own
        n, not N_max's chord."""
        n = 4
        params_n = EnvParams(num_agents=n)
        params_pad = EnvParams(num_agents=N_MAX)
        assert params_n.desired_neighbor_dist != pytest.approx(
            params_pad.desired_neighbor_dist
        )
        small, padded = make_padded_state(
            jax.random.PRNGKey(3), n, params_n, params_pad
        )
        _, tr_small = step(small, jnp.zeros((n, 2)), params_n)
        _, tr_pad = hetero_step(padded, jnp.zeros((N_MAX, 2)), params_pad)
        np.testing.assert_allclose(
            np.asarray(tr_pad.reward[:n]),
            np.asarray(tr_small.reward),
            rtol=1e-5,
            atol=1e-5,
        )


class TestAutoResetAndObstacles:
    def test_auto_reset_preserves_counts(self):
        params = EnvParams(num_agents=N_MAX, num_obstacles=4)
        state = hetero_reset(
            jax.random.PRNGKey(0),
            params,
            jnp.asarray(5, jnp.int32),
            jnp.asarray(2, jnp.int32),
        )
        state = dataclasses.replace(
            state, steps=jnp.asarray(params.max_steps + 1, jnp.int32)
        )
        next_state, tr = hetero_step(state, jnp.zeros((N_MAX, 2)), params)
        assert bool(tr.done)
        assert int(next_state.steps) == 0
        assert int(next_state.n_agents) == 5
        assert int(next_state.n_obstacles) == 2

    def test_inactive_obstacles_never_collide(self):
        params = EnvParams(num_agents=4, num_obstacles=3)
        state = hetero_reset(
            jax.random.PRNGKey(1),
            params,
            jnp.asarray(4, jnp.int32),
            jnp.asarray(0, jnp.int32),  # all obstacle slots inactive
        )
        obstacles = np.asarray(state.obstacles)
        assert (obstacles < -1e5).all()  # parked far outside the world
        _, tr = hetero_step(state, jnp.zeros((4, 2)), params)
        # no obstacle penalty possible: rewards bounded below by other terms
        assert np.asarray(tr.reward).min() > -params.obstacle_penalty

    def test_active_obstacle_penalizes(self):
        params = EnvParams(
            num_agents=4, num_obstacles=1, obstacle_mode="fixed"
        )
        state = hetero_reset(
            jax.random.PRNGKey(2),
            params,
            jnp.asarray(4, jnp.int32),
            jnp.asarray(1, jnp.int32),
        )
        # drop agent 0 onto the obstacle center
        agents = state.agents.at[0].set(state.obstacles[0])
        state = dataclasses.replace(state, agents=agents)
        _, tr = hetero_step(state, jnp.zeros((4, 2)), params)
        assert float(tr.reward[0]) < -50.0  # obstacle penalty dominates


class TestWeightedPPO:
    def test_zero_weight_rows_do_not_change_loss(self):
        key = jax.random.PRNGKey(0)
        model = MLPActorCritic(act_dim=2)
        params = model.init(key, jnp.zeros((1, 8)))
        cfg = PPOConfig()

        b = 32
        obs = jax.random.normal(key, (b, 8))
        act = jax.random.normal(jax.random.PRNGKey(1), (b, 2))
        lp = jax.random.normal(jax.random.PRNGKey(2), (b,))
        adv = jax.random.normal(jax.random.PRNGKey(3), (b,))
        ret = jax.random.normal(jax.random.PRNGKey(4), (b,))

        active = MinibatchData(
            obs=obs, actions=act, old_log_probs=lp, advantages=adv,
            returns=ret, weights=jnp.ones((b,)),
        )
        # corrupt half the rows, weight them zero
        junk = 1e3
        padded = MinibatchData(
            obs=jnp.concatenate([obs, obs + junk]),
            actions=jnp.concatenate([act, act - junk]),
            old_log_probs=jnp.concatenate([lp, lp + junk]),
            advantages=jnp.concatenate([adv, adv * junk]),
            returns=jnp.concatenate([ret, ret - junk]),
            weights=jnp.concatenate([jnp.ones((b,)), jnp.zeros((b,))]),
        )
        loss_a, _ = ppo_loss(params, model.apply, active, cfg)
        loss_p, _ = ppo_loss(params, model.apply, padded, cfg)
        np.testing.assert_allclose(
            float(loss_a), float(loss_p), rtol=1e-5
        )

    def test_none_weights_matches_uniform(self):
        key = jax.random.PRNGKey(5)
        model = MLPActorCritic(act_dim=2)
        params = model.init(key, jnp.zeros((1, 8)))
        cfg = PPOConfig()
        b = 16
        # Independent draws per field (graftlint prng-key-reuse: one key
        # across all five would correlate advantages with returns etc.).
        ks = jax.random.split(key, 5)
        data = dict(
            obs=jax.random.normal(ks[0], (b, 8)),
            actions=jax.random.normal(ks[1], (b, 2)),
            old_log_probs=jax.random.normal(ks[2], (b,)),
            advantages=jax.random.normal(ks[3], (b,)),
            returns=jax.random.normal(ks[4], (b,)),
        )
        loss_none, _ = ppo_loss(
            params, model.apply, MinibatchData(**data), cfg
        )
        loss_ones, _ = ppo_loss(
            params,
            model.apply,
            MinibatchData(**data, weights=jnp.ones((b,))),
            cfg,
        )
        np.testing.assert_allclose(
            float(loss_none), float(loss_ones), rtol=1e-5
        )


class TestCurriculum:
    def test_sample_stage_counts(self):
        stage = CurriculumStage(
            rollouts=1, agent_counts=(5, 20), num_obstacles=3
        )
        n_agents, n_obstacles = sample_stage_counts(
            jax.random.PRNGKey(0), stage, 256
        )
        vals = set(np.asarray(n_agents).tolist())
        assert vals == {5, 20}
        assert (np.asarray(n_obstacles) == 3).all()

    def test_probs_respected(self):
        stage = CurriculumStage(
            rollouts=1, agent_counts=(5, 20), probs=(1.0, 0.0)
        )
        n_agents, _ = sample_stage_counts(jax.random.PRNGKey(1), stage, 64)
        assert (np.asarray(n_agents) == 5).all()

    def test_curriculum_maxima(self):
        cur = Curriculum()
        assert cur.max_agents == 20
        assert cur.max_obstacles == 4
        assert cur.total_rollouts == 100

    def test_vec_env_mixed_batch(self):
        params = EnvParams(num_agents=20, num_obstacles=4)
        reset_fn, step_fn = make_hetero_vec_env(params)
        n_agents = jnp.asarray([5, 20, 7, 2], jnp.int32)
        n_obstacles = jnp.asarray([0, 4, 2, 0], jnp.int32)
        state, obs = reset_fn(jax.random.PRNGKey(0), n_agents, n_obstacles)
        assert obs.shape == (4, 20, params.obs_dim)
        actions = jax.random.uniform(
            jax.random.PRNGKey(1), (4, 20, 2), minval=-1.0, maxval=1.0
        )
        state, tr = step_fn(state, actions)
        assert tr.reward.shape == (4, 20)
        # padding rows of formation 0 (n=5) inert
        assert not np.any(np.asarray(tr.reward[0, 5:]))
        assert np.isfinite(np.asarray(tr.reward)).all()


class TestHeteroTrainer:
    @pytest.mark.slow
    def test_short_curriculum_run(self, tmp_path):
        cur = Curriculum(
            stages=(
                CurriculumStage(rollouts=2, agent_counts=(3,)),
                CurriculumStage(
                    rollouts=2, agent_counts=(3, 6), num_obstacles=2
                ),
            )
        )
        ppo = PPOConfig(n_steps=4, n_epochs=2, batch_size=32)
        trainer = HeteroTrainer(
            curriculum=cur,
            env_params=EnvParams(num_agents=3, max_steps=16),
            ppo=ppo,
            config=TrainConfig(
                num_formations=8,
                name="hetero-test",
                log_dir=str(tmp_path),
                save_freq=10_000,
                use_wandb=False,
            ),
        )
        assert trainer.env_params.num_agents == 6
        assert trainer.env_params.num_obstacles == 2
        record = trainer.train()
        assert np.isfinite(record["loss"])
        assert np.isfinite(record["reward"])
        assert record["curriculum_stage"] == 1.0
        # active-agent timestep accounting: stage rollouts * n_steps * sum(n)
        assert trainer.num_timesteps > 0

    @pytest.mark.slow
    def test_resume_skips_completed_stages(self, tmp_path):
        cur = Curriculum(
            stages=(
                CurriculumStage(rollouts=2, agent_counts=(3,)),
                CurriculumStage(rollouts=2, agent_counts=(4,)),
            )
        )
        kwargs = dict(
            curriculum=cur,
            env_params=EnvParams(num_agents=4, max_steps=16),
            ppo=PPOConfig(n_steps=2, n_epochs=1, batch_size=16),
        )
        config = TrainConfig(
            num_formations=4,
            name="hetero-resume",
            log_dir=str(tmp_path),
            save_freq=10_000,
            use_wandb=False,
        )
        first = HeteroTrainer(config=config, **kwargs)
        first.start_stage(cur.stages[0])
        first.run_iteration()
        first.run_iteration()
        assert first.completed_rollouts == 2  # stage 0 done
        first.save()

        resumed = HeteroTrainer(
            config=dataclasses.replace(config, resume=True), **kwargs
        )
        assert resumed.completed_rollouts == 2
        record = resumed.train()
        # only stage 1 ran: 2 rollouts * 2 n_steps * 4 formations * 4 agents
        assert resumed.completed_rollouts == 4
        assert (
            resumed.num_timesteps
            == first.num_timesteps + 2 * 2 * 4 * 4
        )
        assert record["curriculum_stage"] == 1.0

    @pytest.mark.slow
    def test_sharded_hetero_trainer(self, tmp_path):
        """Curriculum training with the formation axis sharded over 'dp'
        (the cfg.mesh path): stage transitions must re-place the fresh env
        state on the mesh and the run must stay finite."""
        from marl_distributedformation_tpu.parallel import make_shard_fn

        shard_fn = make_shard_fn({"dp": 4})
        cur = Curriculum(
            stages=(
                CurriculumStage(rollouts=2, agent_counts=(3,)),
                CurriculumStage(rollouts=2, agent_counts=(3, 4)),
            )
        )
        trainer = HeteroTrainer(
            curriculum=cur,
            env_params=EnvParams(num_agents=4, max_steps=16),
            ppo=PPOConfig(n_steps=2, n_epochs=1, batch_size=16),
            config=TrainConfig(
                num_formations=8,
                name="hetero-sharded",
                log_dir=str(tmp_path),
                save_freq=10_000,
                use_wandb=False,
            ),
            shard_fn=shard_fn,
        )
        trainer.start_stage(cur.stages[0])
        sharding = trainer.obs.sharding
        assert sharding.is_equivalent_to(
            jax.NamedSharding(shard_fn.mesh, jax.sharding.PartitionSpec("dp")),
            trainer.obs.ndim,
        )
        record = trainer.train()
        assert np.isfinite(record["loss"])
        assert trainer.completed_rollouts == 4

    def test_curriculum_from_cfg_parses_yaml_string(self):
        from marl_distributedformation_tpu.train import curriculum_from_cfg

        cur = curriculum_from_cfg(
            "[{rollouts: 4, agent_counts: [5]}, "
            "{rollouts: 2, agent_counts: [5, 20], num_obstacles: 4}]"
        )
        assert cur.total_rollouts == 6
        assert cur.max_agents == 20
        assert cur.max_obstacles == 4


class TestMaskedCTDE:
    """Mask-aware per-formation (CTDE) training under the curriculum
    (VERDICT.md round-1 #3): padded agents have value 0, contribute no
    gradient, and the update is invariant to padding."""

    def _minibatch(self, obs, actions, logp, adv, ret, w):
        return MinibatchData(
            obs=obs, actions=actions, old_log_probs=logp,
            advantages=adv, returns=ret, weights=w, mask=w,
        )

    @pytest.mark.slow
    def test_update_padding_invariance(self):
        from marl_distributedformation_tpu.models import CTDEActorCritic

        n, n_max, b, obs_dim = 5, 8, 6, 8
        model = CTDEActorCritic(act_dim=2)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, n, obs_dim), jnp.float32)
        )
        rng = np.random.default_rng(0)
        f32 = lambda *s: jnp.asarray(rng.normal(size=s), jnp.float32)
        obs, actions = f32(b, n, obs_dim), f32(b, n, 2)
        logp, adv, ret = f32(b, n), f32(b, n), f32(b, n)

        def pad(x, fill):
            shape = (b, n_max - n) + x.shape[2:]
            return jnp.concatenate(
                [x, jnp.full(shape, fill, x.dtype)], axis=1
            )

        cfg = PPOConfig()
        grad_fn = jax.grad(
            lambda p, mb: ppo_loss(p, model.apply, mb, cfg)[0]
        )
        g_unpadded = grad_fn(
            params,
            self._minibatch(obs, actions, logp, adv, ret, jnp.ones((b, n))),
        )
        w_padded = pad(jnp.ones((b, n)), 0.0)
        g_padded = grad_fn(
            params,
            self._minibatch(
                pad(obs, 3.7), pad(actions, 0.5), pad(logp, 9.9),
                pad(adv, -2.0), pad(ret, 4.0), w_padded,
            ),
        )
        for a, c in zip(
            jax.tree_util.tree_leaves(g_unpadded),
            jax.tree_util.tree_leaves(g_padded),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), rtol=1e-4, atol=1e-6
            )

        # Padded-slot CONTENT is invisible: same grads for any fill values.
        g_padded2 = grad_fn(
            params,
            self._minibatch(
                pad(obs, -11.0), pad(actions, 2.5), pad(logp, 0.0),
                pad(adv, 8.0), pad(ret, -3.0), w_padded,
            ),
        )
        for a, c in zip(
            jax.tree_util.tree_leaves(g_padded),
            jax.tree_util.tree_leaves(g_padded2),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), rtol=1e-5, atol=1e-7
            )

    def test_padded_values_are_zero(self):
        from marl_distributedformation_tpu.models import CTDEActorCritic

        n, n_max, obs_dim = 3, 6, 8
        model = CTDEActorCritic(act_dim=2)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, n_max, obs_dim), jnp.float32)
        )
        obs = jax.random.normal(
            jax.random.PRNGKey(1), (2, n_max, obs_dim), jnp.float32
        )
        mask = (jnp.arange(n_max) < n).astype(jnp.float32)[None].repeat(2, 0)
        _, _, value = model.apply(params, obs, mask)
        assert np.all(np.asarray(value[:, n:]) == 0.0)
        assert np.all(np.asarray(value[:, :n]) != 0.0)

    def test_ctde_curriculum_run(self, tmp_path):
        """policy=ctde under a mixed-size curriculum trains end to end."""
        from marl_distributedformation_tpu.models import CTDEActorCritic

        cur = Curriculum(
            stages=(
                CurriculumStage(rollouts=2, agent_counts=(3,)),
                CurriculumStage(
                    rollouts=2, agent_counts=(3, 6), num_obstacles=2
                ),
            )
        )
        trainer = HeteroTrainer(
            curriculum=cur,
            env_params=EnvParams(num_agents=3, max_steps=16),
            ppo=PPOConfig(n_steps=4, n_epochs=2, batch_size=32),
            config=TrainConfig(
                num_formations=8,
                name="hetero-ctde",
                log_dir=str(tmp_path),
                save_freq=10_000,
                use_wandb=False,
            ),
            model=CTDEActorCritic(act_dim=2),
        )
        assert trainer.per_formation
        before = jax.tree_util.tree_leaves(trainer.train_state.params)
        before = [np.asarray(x).copy() for x in before]
        record = trainer.train()
        assert np.isfinite(record["loss"])
        assert np.isfinite(record["reward"])
        after = jax.tree_util.tree_leaves(trainer.train_state.params)
        assert any(
            not np.allclose(a, b) for a, b in zip(before, after)
        ), "CTDE params did not update under the curriculum"

    @pytest.mark.slow
    def test_train_py_builds_ctde_curriculum(self, tmp_path):
        """The CLI path accepts policy=ctde with a curriculum."""
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
        import train as train_mod

        cfg = train_mod.load_config(
            [
                "name=ctde-cli",
                "policy=ctde",
                "num_formation=4",
                "curriculum=[{rollouts: 1, agent_counts: [3]}]",
                f"log_dir={tmp_path}",
            ]
        )
        trainer = train_mod.build_trainer(cfg)
        assert trainer.per_formation
        trainer.start_stage(trainer.curriculum.stages[0])
        metrics = trainer.run_iteration()
        assert np.isfinite(float(metrics["loss"]))

    def test_hetero_trainer_rejects_sp_mesh(self, tmp_path):
        from marl_distributedformation_tpu.parallel import make_shard_fn

        with pytest.raises(ValueError, match="sp"):
            HeteroTrainer(
                curriculum=Curriculum(
                    stages=(CurriculumStage(rollouts=1, agent_counts=(4,)),)
                ),
                env_params=EnvParams(num_agents=4),
                config=TrainConfig(
                    num_formations=4, log_dir=str(tmp_path), checkpoint=False
                ),
                shard_fn=make_shard_fn({"dp": 2, "sp": 2}),
            )
