"""Live-metrics plane contract (obs/metrics.py + obs/sentinel.py):
registry concurrency, merged-namespace exposition, the telemetry
endpoint, and the perf-regression sentinel.

The registry is pure host-side bookkeeping (no jax import in obs/), so
most of these are fast unit tests; the sentinel e2e at the bottom runs
a real fused-scan trainer twice at the same seed — once healthy, once
deliberately throttled — and pins that the sentinel trips ONLY on the
throttled run, dumps the flight record, and never costs a compile
(budget-1 RetraceGuard receipt with telemetry on).
"""

import json
import re
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from marl_distributedformation_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    RegressionSentinel,
    TelemetryServer,
    Tracer,
    Watch,
    default_watches,
    get_registry,
    load_bench_record,
    prometheus_exposition,
    set_registry,
    set_tracer,
)

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Registry: recording, merging, bounds
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("reqs_total").inc()
    reg.counter("reqs_total").inc(2.0)
    reg.gauge("depth").set(3)
    for v in (1.0, 2.0, 3.0, 10.0):
        reg.histogram("lat_seconds").observe(v)
    snap = reg.snapshot()
    assert snap["reqs_total"] == 3.0
    assert snap["depth"] == 3.0
    assert snap["lat_seconds_count"] == 4.0
    assert snap["lat_seconds_sum"] == 16.0
    assert snap["lat_seconds_p50"] == 3.0  # nearest-rank on the window
    assert snap["lat_seconds_p99"] == 10.0
    assert snap["lat_seconds_p50"] <= snap["lat_seconds_p95"]


def test_disabled_registry_records_nothing():
    reg = MetricsRegistry(enabled=False)
    reg.counter("c_total").inc()
    reg.gauge("g").set(1.0)
    reg.histogram("h").observe(1.0)
    reg.record_gauges({"x": 1.0})
    assert reg.snapshot() == {}
    # Re-enabled, the same handles record again.
    reg.enabled = True
    reg.counter("c_total").inc()
    assert reg.snapshot() == {"c_total": 1.0}


def test_multithread_counts_are_exact_and_snapshots_consistent():
    """Sustained recording from 5 threads while the main thread
    snapshots concurrently: no count is ever lost, and every
    mid-flight snapshot is internally consistent (counters monotone,
    histogram count never exceeds the true total)."""
    reg = MetricsRegistry(reservoir=64)
    per_thread, n_threads = 2000, 5
    stop = threading.Event()

    def hammer(i):
        for k in range(per_thread):
            reg.counter("work_total").inc()
            reg.histogram("work_seconds").observe(float(k % 7))
            reg.gauge(f"worker{i}_progress").set(k)

    threads = [
        threading.Thread(target=hammer, args=(i,)) for i in range(n_threads)
    ]
    seen = []

    def watcher():
        while not stop.is_set():
            seen.append(reg.snapshot().get("work_total", 0.0))

    w = threading.Thread(target=watcher)
    w.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    w.join()
    total = float(per_thread * n_threads)
    snap = reg.snapshot()
    assert snap["work_total"] == total
    assert snap["work_seconds_count"] == total
    # Mid-flight observations never exceeded the true total and are
    # monotone nondecreasing (sums of per-thread monotone shards).
    assert all(v <= total for v in seen)
    assert all(b >= a for a, b in zip(seen, seen[1:]))


def test_gauge_last_write_wins_across_threads():
    reg = MetricsRegistry()
    reg.gauge("g").set(1.0)

    def late_writer():
        reg.gauge("g").set(42.0)

    t = threading.Thread(target=late_writer)
    t.start()
    t.join()
    assert reg.snapshot()["g"] == 42.0
    reg.gauge("g").set(7.0)  # main thread writes after: it wins now
    assert reg.snapshot()["g"] == 7.0


def test_many_short_lived_threads_never_lose_counts():
    """The AsyncCheckpointWriter pattern: one fresh thread per write,
    dying immediately. Dead shards fold into retired accumulators, so
    counter totals stay exact and histogram percentiles stay visible
    across far more dead threads than any bounded shard queue would
    hold — and the live shard map does not grow one entry per corpse."""
    reg = MetricsRegistry(reservoir=32)
    n_threads = 64

    def one_write(i):
        reg.counter("writes_total").inc()
        reg.histogram("write_seconds").observe(float(i))

    for i in range(n_threads):
        t = threading.Thread(target=one_write, args=(i,))
        t.start()
        t.join()
    snap = reg.snapshot()
    assert snap["writes_total"] == float(n_threads)
    assert snap["write_seconds_count"] == float(n_threads)
    assert snap["write_seconds_sum"] == float(sum(range(n_threads)))
    # Percentiles come from the bounded retired-sample pool (every
    # recording thread is dead by now).
    assert snap["write_seconds_p50"] > 0.0
    # Dead idents were swept or recycled — the shard map is bounded by
    # LIVE threads, not by the total ever seen.
    assert len(reg._shards) <= threading.active_count() + 1


def test_reservoir_resize_keeps_counter_totals():
    reg = MetricsRegistry(reservoir=8)
    reg.counter("c_total").inc(5)
    reg.reservoir = 16  # configure_metrics path: shard is retired, not lost
    reg.counter("c_total").inc(3)
    assert reg.snapshot()["c_total"] == 8.0


def test_record_gauges_folds_flat_snapshots_and_skips_annotations():
    reg = MetricsRegistry()
    reg.record_gauges(
        {"fleet_routed_total": 12, "latency_p95_ms": 3.5, "note": "text"}
    )
    snap = reg.snapshot()
    assert snap["fleet_routed_total"] == 12.0
    assert snap["latency_p95_ms"] == 3.5
    assert "note" not in snap


# ---------------------------------------------------------------------------
# Exposition: the merged namespace's line grammar
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (NaN|[+-]?Inf|[-+0-9.e]+)$"
)


def test_exposition_over_merged_namespace():
    """Registry metrics (counters, gauges, histogram percentiles) and
    serving-family keys render together: every sample parses, counters
    type as counters, percentile triples fold into ONE summary family
    with quantile labels, rung keys keep their labeled families."""
    reg = MetricsRegistry()
    reg.counter("train_iterations_total").inc(9)
    reg.gauge("train_env_steps_per_sec").set(1234.5)
    for v in (0.01, 0.02, 0.03):
        reg.histogram("train_chunk_drain_seconds").observe(v)
    snap = reg.snapshot()
    # The serving families arrive through the same flat-dict shape.
    snap.update(
        {
            "latency_p50_ms": 1.5,
            "latency_p95_ms": 2.5,
            "latency_p99_ms": 3.5,
            "rung512_f32_sharded": 1.0,
            "rung512_f32_sharded_compiles": 1.0,
            "replica0_queue_depth": 0.0,
        }
    )
    text = prometheus_exposition(snap)
    lines = text.strip().splitlines()
    samples = [ln for ln in lines if not ln.startswith("#")]
    for line in samples:
        assert _PROM_LINE.match(line), f"unparseable sample: {line!r}"
    types = {
        ln.split()[2]: ln.split()[3] for ln in lines if ln.startswith("# TYPE")
    }
    assert types["marl_train_iterations_total"] == "counter"
    assert types["marl_train_env_steps_per_sec"] == "gauge"
    # Histogram percentiles fold into one summary family.
    assert types["marl_train_chunk_drain_seconds"] == "summary"
    drain = [
        ln for ln in samples
        if ln.startswith("marl_train_chunk_drain_seconds{")
    ]
    assert {'quantile="0.5"', 'quantile="0.95"', 'quantile="0.99"'} == {
        ln[ln.index("{") + 1 : ln.index("}")] for ln in drain
    }
    # Fleet latency keys fold the same way (naming-drift fix discipline).
    assert types["marl_latency_ms"] == "summary"
    # Rung gauges keep their labeled families (pinned since PR 9).
    assert any(
        ln.startswith("marl_rung_sharded{")
        and 'rung="512"' in ln
        and 'dtype="f32"' in ln
        for ln in samples
    )
    assert any(
        ln.startswith("marl_rung_compiles{") and 'kind="sharded"' in ln
        for ln in samples
    )
    assert any(ln.startswith("marl_queue_depth{replica=") for ln in samples)


def test_exposition_folds_tenant_model_labels():
    """Per-tenant ``model_{id}__{metric}`` keys (serving/tenancy) fold
    into ONE family per metric with a ``model`` label — N lanes are one
    label dimension, not N metric names — and every rendered sample
    still parses under the exposition line grammar. Lane names carry
    the full allowed alphabet (dots, dashes, single underscores); the
    double-underscore delimiter keeps the split unambiguous."""
    snap = {
        "model_formation-a__step": 200.0,
        "model_formation-a__requests_total": 7.0,
        "model_form_b.v2__step": 100.0,
        "model_form_b.v2__requests_total": 3.0,
        "model_pursuit__queue_depth": 0.0,
        # A per-lane percentile composes BOTH folds: model + quantile
        # labels on one summary family.
        "model_pursuit__latency_p95_ms": 2.5,
        "model_step": 200.0,  # no double underscore: stays a plain gauge
    }
    text = prometheus_exposition(snap)
    lines = text.strip().splitlines()
    samples = [ln for ln in lines if not ln.startswith("#")]
    for line in samples:
        assert _PROM_LINE.match(line), f"unparseable sample: {line!r}"
    types = {
        ln.split()[2]: ln.split()[3] for ln in lines if ln.startswith("# TYPE")
    }
    # One family per metric, model-labeled; counters stay counters.
    assert types["marl_model_step"] == "gauge"
    assert types["marl_model_requests_total"] == "counter"
    assert types["marl_model_latency_ms"] == "summary"
    steps = [ln for ln in samples if ln.startswith("marl_model_step{")]
    assert {'model="formation-a"', 'model="form_b.v2"'} == {
        ln[ln.index("{") + 1 : ln.index("}")] for ln in steps
    }
    assert any(
        ln.startswith("marl_model_latency_ms{")
        and 'model="pursuit"' in ln
        and 'quantile="0.95"' in ln
        for ln in samples
    )
    # The fleet-wide max rides the same family name UNlabeled (no
    # double underscore to fold on).
    assert "marl_model_step 200.0" in samples


# ---------------------------------------------------------------------------
# TelemetryServer
# ---------------------------------------------------------------------------


def test_telemetry_server_serves_prometheus_and_json():
    reg = MetricsRegistry()
    reg.counter("ticks_total").inc(4)
    reg.gauge("train_env_steps_per_sec").set(100.0)
    srv = TelemetryServer(
        port=0, registry=reg, extra_snapshot=lambda: {"extra_gauge": 1.0}
    ).start()
    try:
        with urllib.request.urlopen(srv.url, timeout=5) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        for line in body.strip().splitlines():
            if not line.startswith("#"):
                assert _PROM_LINE.match(line), line
        assert "marl_ticks_total 4.0" in body
        assert "marl_extra_gauge 1.0" in body
        with urllib.request.urlopen(
            srv.url.replace("/metrics", "/metrics.json"), timeout=5
        ) as resp:
            snap = json.loads(resp.read())
        assert snap["ticks_total"] == 4.0
        # Unknown path is a 404, not a crash.
        try:
            urllib.request.urlopen(
                srv.url.replace("/metrics", "/nope"), timeout=5
            )
            assert False, "expected HTTP 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_telemetry_server_survives_broken_extra_snapshot():
    reg = MetricsRegistry()
    reg.gauge("g").set(1.0)

    def broken():
        raise RuntimeError("boom")

    srv = TelemetryServer(port=0, registry=reg, extra_snapshot=broken).start()
    try:
        with urllib.request.urlopen(srv.url, timeout=5) as resp:
            assert b"marl_g 1.0" in resp.read()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# RegressionSentinel: bench loading, taxonomy, hysteresis
# ---------------------------------------------------------------------------


def test_load_bench_record_prefers_newest_round_and_unwraps(tmp_path):
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"parsed": {"train_env_steps_per_sec": 2.0}, "n": 2})
    )
    (tmp_path / "BENCH_r10.json").write_text(  # numeric: r10 beats r2
        json.dumps({"train_env_steps_per_sec": 10.0})
    )
    rec, src = load_bench_record(root=tmp_path)
    assert src.name == "BENCH_r10.json"
    assert rec["train_env_steps_per_sec"] == 10.0
    rec2, src2 = load_bench_record(path=tmp_path / "BENCH_r02.json")
    assert rec2["train_env_steps_per_sec"] == 2.0  # wrapper unwrapped
    assert load_bench_record(root=tmp_path / "empty") == ({}, None)


def test_committed_bench_record_loads():
    rec, src = load_bench_record(root=REPO)
    assert src is not None and src.name.startswith("BENCH_r")
    assert rec.get("metric"), "committed record lost its headline field"


def _sentinel(record, trip_after=2, tolerance=0.5, **kwargs):
    return RegressionSentinel(
        [
            Watch(
                gauge="rate",
                bench_fields=("recorded_rate",),
                direction="min",
                tolerance=tolerance,
            )
        ],
        record=record,
        trip_after=trip_after,
        registry=MetricsRegistry(),
        tracer=Tracer(),
        **kwargs,
    )


def test_sentinel_trips_only_after_consecutive_breaches():
    s = _sentinel({"recorded_rate": 100.0}, trip_after=3)
    # limit = 50: 10 breaches, 80 does not.
    assert s.check({"rate": 10.0}) == []
    assert s.check({"rate": 10.0}) == []
    assert s.check({"rate": 80.0}) == []  # streak resets — hysteresis
    assert s.check({"rate": 10.0}) == []
    assert s.check({"rate": 10.0}) == []
    trips = s.check({"rate": 10.0})
    assert len(trips) == 1 and trips[0]["gauge"] == "rate"
    assert trips[0]["limit"] == 50.0 and trips[0]["recorded"] == 100.0
    # Latched: continued degradation does not re-dump...
    assert s.check({"rate": 10.0}) == []
    # ...until recovery re-arms the watch.
    assert s.check({"rate": 90.0}) == []
    for _ in range(2):
        s.check({"rate": 10.0})
    assert len(s.check({"rate": 10.0})) == 1
    assert len(s.trips) == 2


def test_sentinel_direction_max_guards_latency():
    s = RegressionSentinel(
        [
            Watch(
                gauge="latency_p95_ms",
                bench_fields=("serving_fleet_p95_ms",),
                direction="max",
                tolerance=0.5,
            )
        ],
        record={"serving_fleet_p95_ms": 10.0},
        trip_after=1,
        registry=MetricsRegistry(),
        tracer=Tracer(),
    )
    assert s.check({"latency_p95_ms": 14.0}) == []  # limit is 15
    assert len(s.check({"latency_p95_ms": 20.0})) == 1


def test_sentinel_missing_bench_field_taxonomy_never_trips():
    s = RegressionSentinel(
        [
            Watch("a", ("absent_field",), "min", 0.5),
            Watch("b", ("skipped_field",), "min", 0.5),
            Watch("c", ("text_field",), "min", 0.5),
        ],
        record={"skipped_field": "skipped", "text_field": "notanumber"},
        trip_after=1,
        registry=MetricsRegistry(),
        tracer=Tracer(),
    )
    for _ in range(3):
        assert s.check({"a": 0.0, "b": 0.0, "c": 0.0}) == []
    assert s.trips == []
    assert "absent" in s.missing["a"]
    assert "skipped" in s.missing["b"]
    assert "non-numeric" in s.missing["c"]
    assert s.summary()["sentinel_missing"]  # surfaced, not silent


def test_sentinel_missing_live_gauge_is_not_evidence():
    s = _sentinel({"recorded_rate": 100.0}, trip_after=2)
    assert s.check({"rate": 10.0}) == []
    for _ in range(5):
        assert s.check({}) == []  # cold gauge: streak untouched, no trip
    assert len(s.check({"rate": 10.0})) == 1  # streak was preserved


def test_sentinel_trip_dumps_flightrec_and_audit_line(tmp_path):
    tracer = Tracer(flightrec=FlightRecorder(tmp_path, last_n=64))
    tracer.event("pre-incident", detail=1)
    s = RegressionSentinel(
        [Watch("rate", ("recorded_rate",), "min", 0.5)],
        record={"recorded_rate": 100.0},
        trip_after=1,
        audit_dir=tmp_path,
        registry=MetricsRegistry(),
        tracer=tracer,
    )
    assert len(s.check({"rate": 1.0})) == 1
    dumps = list(tmp_path.glob("flightrec-perf_regression-*.json"))
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    assert payload["trigger"] == "perf_regression"
    assert payload["context"]["gauge"] == "rate"
    # The metrics snapshot rides in the dump as structured data.
    assert payload["context"]["metrics_snapshot"]["rate"] == 1.0
    # The pre-incident span history is in the record.
    assert any(r["name"] == "pre-incident" for r in payload["records"])
    audit = (tmp_path / "perf_incidents.jsonl").read_text().splitlines()
    assert len(audit) == 1
    line = json.loads(audit[0])
    assert line["event"] == "perf_regression"
    assert line["flightrec"] == str(dumps[0])
    assert line["limit"] == 50.0


def test_sentinel_reports_never_observed_watches():
    """A watch that is measurable against the record but whose live
    gauge nothing feeds must be surfaced as blind, not silently armed
    forever."""
    s = RegressionSentinel(
        [
            Watch("fed", ("f1",), "min", 0.5),
            Watch("starved", ("f2",), "min", 0.5),
        ],
        record={"f1": 100.0, "f2": 100.0},
        trip_after=2,
        registry=MetricsRegistry(),
        tracer=Tracer(),
    )
    s.check({"fed": 90.0})
    summary = s.summary()
    assert summary["sentinel_never_observed"] == ["starved"]
    assert "fed" not in summary["sentinel_never_observed"]
    s.check({"fed": 90.0, "starved": 90.0})
    assert s.summary()["sentinel_never_observed"] == []


def test_default_watches_cover_the_three_lanes():
    gauges = {w.gauge for w in default_watches()}
    assert gauges == {
        "train_env_steps_per_sec",
        "gate_eval_steps_per_sec",
        "latency_p95_ms",
    }
    with pytest.raises(ValueError):
        Watch("g", ("f",), direction="sideways")
    with pytest.raises(ValueError):
        Watch("g", (), direction="min")


# ---------------------------------------------------------------------------
# RollbackMonitor over the registry: one sampling path fleet-wide
# ---------------------------------------------------------------------------


def test_rollback_monitor_samples_the_registry_namespace():
    from marl_distributedformation_tpu.pipeline import RollbackMonitor

    reg = MetricsRegistry()
    reg.gauge("latency_p95_ms").set(5.0)
    monitor = RollbackMonitor(
        reg.snapshot, metric="latency_p95_ms", threshold=10.0,
        direction="above", trip_after=2,
    )
    assert not monitor.observe()
    reg.gauge("latency_p95_ms").set(50.0)
    assert not monitor.observe()  # first breach
    assert monitor.observe()  # second: trips — semantics unchanged
    # Any registry key is watchable now, not just fleet snapshot keys.
    reg.gauge("train_env_steps_per_sec").set(1.0)
    m2 = RollbackMonitor(
        reg.snapshot, metric="train_env_steps_per_sec", threshold=10.0,
        direction="below", trip_after=1,
    )
    assert m2.observe()


# ---------------------------------------------------------------------------
# Trainer instrumentation + the sentinel e2e (healthy vs throttled)
# ---------------------------------------------------------------------------


def _tiny_trainer(tmp_path, name, trainer_cls=None):
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    cls = trainer_cls or Trainer
    return cls(
        EnvParams(num_agents=3, max_steps=20),
        ppo=PPOConfig(n_steps=4, n_epochs=1, batch_size=24),
        config=TrainConfig(
            num_formations=4,
            # 6 chunks of 4 iterations: per iteration the budget burns
            # n_steps(4) * num_formations(4) * num_agents(3) transitions.
            total_timesteps=6 * 4 * 4 * 4 * 3,
            seed=0,
            fused_chunk=4,
            name=name,
            log_dir=str(tmp_path / name),
            save_freq=1000,
        ),
    )


def test_trainer_records_lane_metrics_into_registry(tmp_path):
    prev = set_registry(MetricsRegistry())
    try:
        trainer = _tiny_trainer(tmp_path, "metrics_plain")
        trainer.train()
        snap = get_registry().snapshot()
        assert snap["train_iterations_total"] == 24.0
        assert snap["train_chunks_total"] == 6.0
        assert snap["train_env_steps_per_sec"] > 0.0
        assert snap["train_steps_per_sec"] > 0.0
        assert snap["train_chunk_drain_seconds_count"] == 6.0
        assert snap["train_chunk_drain_seconds_p50"] >= 0.0
        # The live compile counter is the budget-1 receipt.
        assert snap["train_compiles"] == 1.0
        # Async checkpoint writer health (save_freq forced one final
        # save): queue drained, write latency observed.
        assert snap["checkpoint_writes_total"] >= 1.0
        assert snap["checkpoint_queue_depth"] == 0.0
        assert snap["checkpoint_write_seconds_count"] >= 1.0
    finally:
        set_registry(prev)


class _ThrottledTrainerMixin:
    """A deliberately slowed dispatch loop — the contended-host /
    degraded-device failure mode the sentinel exists to catch. The
    compiled program is untouched (same compile receipt); only the
    host loop drags. THROTTLE_S is set per test run, scaled off the
    measured healthy chunk time so the regression margin survives a
    loaded CI machine."""

    THROTTLE_S = 0.12

    def run_chunk(self):
        time.sleep(self.THROTTLE_S)
        return super().run_chunk()


def test_sentinel_e2e_trips_on_throttled_run_never_on_healthy(tmp_path):
    """The acceptance e2e: same-seed run pair through the REAL fused
    trainer. The healthy run's throughput sets the committed-record
    reference; the sentinel never trips on it, trips (with a flight
    record and audit line) on the throttled twin, and the budget-1
    compile receipt holds through both with telemetry ON."""
    from marl_distributedformation_tpu.train import Trainer

    # -- healthy run: establishes the recorded reference ----------------
    prev_reg = set_registry(MetricsRegistry())
    prev_tracer = set_tracer(Tracer())
    try:
        healthy = _tiny_trainer(tmp_path, "sentinel_healthy")
        healthy.train()
        healthy_snap = get_registry().snapshot()
        healthy_rate = healthy_snap["train_env_steps_per_sec"]
        assert healthy_rate > 0.0
        assert healthy.retrace_guard.count == 1
        bench_record = {"train_env_steps_per_sec_fused_scan": healthy_rate}
        sentinel = RegressionSentinel(
            default_watches(tolerance=0.5),
            record=bench_record,
            trip_after=2,
            audit_dir=tmp_path / "healthy_audit",
        )
        for _ in range(5):
            assert sentinel.check() == [], (
                "sentinel tripped on a healthy same-seed run"
            )
        assert sentinel.trips == []
    finally:
        set_registry(prev_reg)
        set_tracer(prev_tracer)

    # -- throttled run: same seed/config, dragged host loop -------------
    class ThrottledTrainer(_ThrottledTrainerMixin, Trainer):
        # 10x the healthy chunk's wall time (floor 0.12s): the throttled
        # rate lands near healthy/10, far below the 0.5*recorded limit
        # even when a loaded machine slowed the healthy run itself.
        THROTTLE_S = max(0.12, 10 * 64.0 / healthy_rate)

    flight_dir = tmp_path / "throttled_flight"
    prev_reg = set_registry(MetricsRegistry())
    prev_tracer = set_tracer(
        Tracer(flightrec=FlightRecorder(flight_dir, last_n=128))
    )
    try:
        throttled = _tiny_trainer(
            tmp_path, "sentinel_throttled", trainer_cls=ThrottledTrainer
        )
        sentinel = RegressionSentinel(
            default_watches(tolerance=0.5),
            record=bench_record,
            trip_after=2,
            audit_dir=flight_dir,
        )
        throttled.train()
        # The throttle dominates the tiny chunk: the live rate sits far
        # below half the healthy rate, so two checks trip the watch.
        live = get_registry().snapshot()["train_env_steps_per_sec"]
        assert live < 0.5 * healthy_rate, (
            f"throttle too weak to regress: {live} vs {healthy_rate}"
        )
        sentinel.check()
        trips = sentinel.check()
        assert len(trips) == 1
        assert trips[0]["gauge"] == "train_env_steps_per_sec"
        assert trips[0]["bench_field"] == "train_env_steps_per_sec_fused_scan"
        # Flight record + audit line landed.
        dumps = list(flight_dir.glob("flightrec-perf_regression-*.json"))
        assert len(dumps) == 1
        payload = json.loads(dumps[0].read_text())
        assert (
            payload["context"]["metrics_snapshot"]["train_env_steps_per_sec"]
            == live
        )
        assert (flight_dir / "perf_incidents.jsonl").exists()
        # Telemetry + throttling never cost a compile: budget-1 holds.
        assert throttled.retrace_guard.count == 1
    finally:
        set_registry(prev_reg)
        set_tracer(prev_tracer)
