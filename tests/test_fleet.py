"""Serving fleet contract (tier-1, multi-device CPU): load-aware
routing, replica-kill failover, coordinated hot-swap step monotonicity,
and the HTTP frontend round trip.

The acceptance pins from the fleet ISSUE live here, exercised on the
8-virtual-device CPU mesh tests/conftest.py provisions (the same
`--xla_force_host_platform_device_count` mechanism the ISSUE names):

- a mixed-size request storm over >= 2 replicas completes with zero
  recompiles beyond one-per-rung-per-replica (RetraceGuard receipts);
- a replica killed mid-storm loses no accepted in-flight requests —
  its queued futures transparently fail over to surviving replicas;
- a mid-storm coordinated hot swap yields globally step-monotonic
  ``model_step``s in responses (the batch-barrier commit, fleet/reload);
- the stdlib HTTP frontend round-trips act/health/metrics on an
  ephemeral port with JSON backpressure (429 + Retry-After).
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.compat.policy import (  # noqa: E402
    LoadedPolicy,
)
from marl_distributedformation_tpu.models import MLPActorCritic  # noqa: E402
from marl_distributedformation_tpu.serving import (  # noqa: E402
    BackpressureError,
    ServingClient,
)
from marl_distributedformation_tpu.serving.fleet import (  # noqa: E402
    FleetFrontend,
    FleetReloadCoordinator,
    FleetRouter,
    NoHealthyReplicas,
    fleet_from_checkpoint_dir,
    run_fleet_smoke,
    warmup_fleet,
)
from marl_distributedformation_tpu.utils.checkpoint import (  # noqa: E402
    save_checkpoint,
)

OBS_DIM = 6
HIDDEN = (8, 8)


def _make_policy(seed=0, hidden=HIDDEN, obs_dim=OBS_DIM):
    model = MLPActorCritic(act_dim=2, hidden=hidden)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim)))
    return LoadedPolicy(dict(variables), model_kwargs={"hidden": hidden})


def _write_ckpt(log_dir, step, policy):
    return save_checkpoint(
        log_dir,
        step,
        {
            "policy": type(policy.model).__name__,
            "params": policy.params,
            "num_timesteps": step,
        },
    )


def _obs(n, seed=0):
    return (
        np.random.default_rng(seed)
        .standard_normal((n, OBS_DIM))
        .astype(np.float32)
    )


def _slow_engine(engine, delay_s):
    """Wrap engine.act with a delay AFTER warmup, so queues actually
    build and routing/failover behavior becomes observable."""
    orig = engine.act

    def slow_act(*args, **kwargs):
        time.sleep(delay_s)
        return orig(*args, **kwargs)

    engine.act = slow_act
    return engine


def test_fleet_requires_multiple_devices():
    """The whole point of the conftest mesh: these tests must exercise a
    REAL multi-device fleet, not N replicas piled on one device."""
    assert len(jax.local_devices()) >= 4


def test_replicas_land_on_distinct_devices():
    router = FleetRouter(_make_policy(), num_replicas=3, buckets=(1, 8))
    devices = [r.device for r in router.replicas]
    assert len(set(devices)) == 3
    for r in router.replicas:
        params, step = r.registry.active()
        leaf = jax.tree_util.tree_leaves(params)[0]
        assert leaf.devices() == {r.device}


def test_router_routes_around_a_slow_replica():
    """Routing skew under uneven load: the drain-time estimator must
    shift traffic off a replica whose device got slow (its in-flight
    batch counts as backlog, not just its queue)."""
    policy = _make_policy()
    router = FleetRouter(
        policy, num_replicas=2, buckets=(1, 8), window_ms=0.0
    )
    warmup_fleet(router, (OBS_DIM,))
    _slow_engine(router.replicas[0].engine, 0.15)
    with router:
        futures = []
        deadline = time.perf_counter() + 1.0
        while time.perf_counter() < deadline:
            futures.append(router.submit(_obs(2, seed=len(futures))))
            time.sleep(0.01)
        results = [f.result(timeout=30) for f in futures]
    assert all(r.actions.shape == (2, 2) for r in results)
    served = {
        i: router.replicas[i].scheduler.metrics.requests_total
        for i in (0, 1)
    }
    # The slow replica serves SOME traffic (it is healthy, just slow)
    # but the fast one must carry the clear majority.
    assert served[1] > 2 * max(1, served[0]), served
    assert router.metrics.routed_per_replica()[1] > served[0]


def test_replica_kill_loses_no_accepted_requests():
    """The failover pin: kill a replica with requests in its queue —
    every accepted future still resolves (re-routed to the survivor),
    the dead replica is circuit-broken, and the fleet keeps serving."""
    policy = _make_policy()
    router = FleetRouter(
        policy,
        num_replicas=2,
        buckets=(1, 8),
        window_ms=0.0,
        probe_interval_s=0.05,
        max_failovers=2,
    )
    warmup_fleet(router, (OBS_DIM,))
    _slow_engine(router.replicas[0].engine, 0.1)
    ref, _ = policy.predict(_obs(2, seed=1), deterministic=True)
    with router:
        # Quarantine replica 1 so every submit lands on replica 0 and
        # its queue demonstrably holds accepted requests at kill time.
        router._break(router.replicas[1], "test quarantine")
        first = router.submit(_obs(2, seed=1))
        time.sleep(0.03)  # worker picks it up and blocks in the engine
        queued = [router.submit(_obs(2, seed=1)) for _ in range(5)]
        assert router.replicas[0].scheduler.queue_depth > 0
        router.kill_replica(0)
        # All six resolve: the in-flight one on replica 0, the queued
        # ones by failover onto replica 1 (readmitted by the half-open
        # probe once its interval elapsed).
        for fut in [first] + queued:
            res = fut.result(timeout=30)
            np.testing.assert_allclose(
                res.actions, ref, rtol=1e-5, atol=1e-6
            )
        assert not router.replicas[0].healthy
        assert router.metrics.failed_over_total >= len(queued)
        assert router.healthy_replicas == 1
        # The fleet still serves new traffic through the survivor.
        res = router.submit(_obs(3, seed=2)).result(timeout=30)
        assert res.actions.shape == (3, 2)
        assert res.replica == 1


def test_all_replicas_broken_raises_no_healthy():
    router = FleetRouter(
        _make_policy(), num_replicas=2, buckets=(1,),
        probe_interval_s=60.0,
    )
    with router:
        router.kill_replica(0)
        router.kill_replica(1)
        with pytest.raises(NoHealthyReplicas):
            router.submit(_obs(1))


def test_fleet_backpressure_aggregates_min_retry_after():
    """Fleet-level backpressure only when EVERY healthy replica is full,
    quoting the smallest retry_after any replica priced."""
    router = FleetRouter(
        _make_policy(), num_replicas=2, buckets=(1, 8),
        window_ms=0.0, max_queue=1,
    )
    warmup_fleet(router, (OBS_DIM,))
    for r in router.replicas:
        _slow_engine(r.engine, 0.3)
    with router:
        accepted = []
        rejected = None
        for i in range(12):
            try:
                accepted.append(router.submit(_obs(1, seed=i)))
            except BackpressureError as e:
                rejected = e
                break
        assert rejected is not None, "fleet queue bound never engaged"
        assert rejected.retry_after_s > 0.0
        assert router.metrics.rejected_total >= 1
        for f in accepted:
            assert f.result(timeout=30).actions.shape == (1, 2)


def test_coordinated_swap_mid_storm_is_globally_step_monotonic(tmp_path):
    """THE acceptance pin: mixed-size storm over 3 replicas; mid-storm
    one replica is killed AND a new checkpoint lands via the
    coordinator. Zero recompiles beyond one-per-rung-per-replica, no
    accepted request lost, and model_steps globally monotonic in
    completion order."""
    watch = tmp_path / "watch"
    stage = tmp_path / "stage"
    _write_ckpt(watch, 100, _make_policy(seed=0))
    # Pre-serialize the step-200 checkpoint off to the side; the chaos
    # hook lands it with one atomic rename (building a policy mid-storm
    # would stall the storm behind a jit init compile).
    staged = _write_ckpt(stage, 200, _make_policy(seed=7))
    router, coordinator = fleet_from_checkpoint_dir(
        watch, num_replicas=3, buckets=(1, 8, 64), window_ms=1.0
    )

    def chaos():
        router.kill_replica(0)
        os.replace(staged, watch / staged.name)
        assert coordinator.refresh(), "newer checkpoint must swap"

    with router:
        report = run_fleet_smoke(
            router,
            row_shape=(OBS_DIM,),
            duration_s=2.0,
            num_clients=4,
            coordinator=coordinator,
            mid_storm=chaos,
            mid_storm_at_s=0.5,
        )
    assert report["client_requests_ok"] > 0
    assert report["client_failed"] == 0.0, report
    assert report["step_monotonic_violations"] == 0.0
    assert report["model_step_min"] == 100.0
    assert report["model_step_max"] == 200.0, (
        "no post-swap response observed — swap never became visible"
    )
    assert report["max_compiles_per_rung"] <= 1.0
    assert report["fleet_swap_count"] == 1.0
    assert report["fleet_step"] == 200.0
    # Every replica swapped exactly once — including the dead one, so a
    # revival would serve the current step, never a stale one.
    assert all(r.registry.swap_count == 1 for r in router.replicas)
    assert all(
        r.registry.active_step == 200 for r in router.replicas
    )


def test_coordinator_polls_once_and_contains_bad_checkpoints(tmp_path):
    """One poller for the whole fleet: a mismatched-architecture
    checkpoint is a recorded error that leaves EVERY replica serving the
    old params; the next good checkpoint swaps them all."""
    _write_ckpt(tmp_path, 10, _make_policy(hidden=(8, 8)))
    router, coordinator = fleet_from_checkpoint_dir(
        tmp_path, num_replicas=2, buckets=(1,)
    )
    _write_ckpt(tmp_path, 20, _make_policy(hidden=(16, 16)))
    assert not coordinator.refresh()
    assert len(coordinator.load_errors) == 1
    assert "rl_model_20_steps" in coordinator.load_errors[0][0]
    assert all(r.registry.active_step == 10 for r in router.replicas)
    _write_ckpt(tmp_path, 30, _make_policy(seed=3, hidden=(8, 8)))
    assert coordinator.refresh()
    assert coordinator.fleet_step == 30
    assert all(r.registry.active_step == 30 for r in router.replicas)
    # Older steps never swap backward, fleet-wide.
    _write_ckpt(tmp_path, 25, _make_policy(seed=4, hidden=(8, 8)))
    assert not coordinator.refresh()
    assert coordinator.fleet_step == 30


def test_coordinator_commit_aborts_cleanly_on_wedged_replica(tmp_path):
    """A replica wedged mid-dispatch (its barrier held indefinitely)
    must not park the fleet behind closed gates or produce a partial
    swap: the commit times out, reopens every gate, records the error,
    and the old step keeps serving everywhere until a later retry."""
    _write_ckpt(tmp_path, 10, _make_policy(seed=0))
    router, coordinator = fleet_from_checkpoint_dir(
        tmp_path, num_replicas=2, buckets=(1, 8), probe_interval_s=60.0
    )
    coordinator.commit_timeout_s = 0.2
    warmup_fleet(router, (OBS_DIM,))
    _write_ckpt(tmp_path, 20, _make_policy(seed=1))
    wedged = router.replicas[1].registry.batch_lock
    wedged.acquire()  # simulate a worker stuck inside a device dispatch
    try:
        with router:
            assert not coordinator.refresh()
            assert coordinator.fleet_step == 10
            # No partial swap: BOTH replicas still serve the old step.
            assert all(
                r.registry.active_step == 10 for r in router.replicas
            )
            assert "commit aborted" in coordinator.load_errors[-1][1]
            # Gates reopened: the rest of the fleet keeps serving (pin
            # routing to the healthy replica — the wedged one would
            # block behind its held barrier).
            router._break(router.replicas[1], "wedged in test")
            res = router.submit(_obs(2, seed=1)).result(timeout=30)
            assert res.model_step == 10
            assert res.replica == 0
    finally:
        wedged.release()
    # The wedge cleared: the next poll lands the swap fleet-wide.
    assert coordinator.refresh()
    assert all(r.registry.active_step == 20 for r in router.replicas)


def test_wedged_abort_incident_fires_after_gates_reopen(tmp_path):
    """Regression: the ``wedged_barrier_abort`` postmortem (a flight-
    recorder file write) must fire AFTER the partially-acquired
    barriers are released and every gate reopened — it used to fire
    from inside the acquisition loop, extending the fleet-wide serving
    pause the wedged barrier already caused by the dump's IO."""
    from marl_distributedformation_tpu.obs import get_tracer

    _write_ckpt(tmp_path, 10, _make_policy(seed=0))
    router, coordinator = fleet_from_checkpoint_dir(
        tmp_path, num_replicas=2, buckets=(1, 8), probe_interval_s=60.0
    )
    coordinator.commit_timeout_s = 0.2
    warmup_fleet(router, (OBS_DIM,))
    candidate = _write_ckpt(tmp_path, 20, _make_policy(seed=1))
    healthy = router.replicas[0].registry.batch_lock
    wedged = router.replicas[1].registry.batch_lock
    wedged.acquire()  # simulate a worker stuck inside a device dispatch
    tracer = get_tracer()
    states = []
    original = tracer.incident

    def spy(name, **fields):
        if name == "wedged_barrier_abort":
            states.append(
                (
                    healthy._lock.locked(),
                    healthy._open.is_set(),
                    wedged._open.is_set(),
                )
            )
        return original(name, **fields)

    tracer.incident = spy
    try:
        with router:
            staged, reason = coordinator.prepare_global(candidate)
    finally:
        tracer.incident = original
        wedged.release()
    assert not staged and "barrier not acquired" in reason
    # Exactly one dump, and at dump time: the healthy replica's barrier
    # is released and BOTH gates are open again (workers unparked).
    assert states == [(False, True, True)], states


def test_coordinator_background_watcher_swaps(tmp_path):
    _write_ckpt(tmp_path, 1, _make_policy(seed=0))
    router, coordinator = fleet_from_checkpoint_dir(
        tmp_path, num_replicas=2, buckets=(1,), poll_interval_s=0.05
    )
    with router, coordinator:
        _write_ckpt(tmp_path, 2, _make_policy(seed=1))
        deadline = time.time() + 10.0
        while coordinator.fleet_step != 2 and time.time() < deadline:
            time.sleep(0.02)
    assert coordinator.fleet_step == 2
    assert coordinator.swap_count == 1


def test_serving_client_works_over_the_router():
    """ServingClient is duck-typed over scheduler-or-router: the same
    client code that talks to one engine talks to the fleet."""
    policy = _make_policy()
    router = FleetRouter(policy, num_replicas=2, buckets=(1, 8))
    warmup_fleet(router, (OBS_DIM,))
    with router:
        client = ServingClient(router, max_retries=1)
        obs = _obs(3, seed=5)
        actions, step = client.predict(obs, deterministic=True)
    ref, _ = policy.predict(obs, deterministic=True)
    np.testing.assert_allclose(actions, ref, rtol=1e-5, atol=1e-6)
    assert step == 0


# ---------------------------------------------------------------------------
# HTTP frontend
# ---------------------------------------------------------------------------


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url + "/v1/act",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


def test_frontend_round_trip_on_ephemeral_port():
    policy = _make_policy()
    router = FleetRouter(
        policy, num_replicas=2, buckets=(1, 8), initial_step=42
    )
    warmup_fleet(router, (OBS_DIM,))
    obs = _obs(3, seed=9)
    ref, _ = policy.predict(obs, deterministic=True)
    with router, FleetFrontend(router, port=0) as frontend:
        assert frontend.port > 0  # ephemeral bind resolved
        body = _post(frontend.url, {"obs": obs.tolist()})
        np.testing.assert_allclose(
            np.asarray(body["actions"], np.float32), ref,
            rtol=1e-5, atol=1e-6,
        )
        assert body["model_step"] == 42
        assert body["replica"] in (0, 1)
        assert body["latency_s"] >= 0.0
        health = json.loads(
            urllib.request.urlopen(
                frontend.url + "/v1/health", timeout=10
            ).read()
        )
        assert health == {
            "healthy_replicas": 2, "replicas": 2, "model_step": 42,
        }
        metrics = json.loads(
            urllib.request.urlopen(
                frontend.url + "/v1/metrics", timeout=10
            ).read()
        )
        assert metrics["fleet_routed_total"] >= 1.0


def test_frontend_maps_failure_taxonomy_to_status_codes():
    router = FleetRouter(
        _make_policy(), num_replicas=1, buckets=(1,),
        window_ms=0.0, max_queue=1, probe_interval_s=60.0,
    )
    warmup_fleet(router, (OBS_DIM,))
    _slow_engine(router.replicas[0].engine, 0.5)
    with router, FleetFrontend(router, port=0) as frontend:
        # Malformed JSON -> 400.
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    frontend.url + "/v1/act", data=b"not json"
                ),
                timeout=10,
            )
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        # Unknown path -> 404.
        try:
            urllib.request.urlopen(frontend.url + "/nope", timeout=10)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # Fill the single replica (one in flight + one queued), then a
        # frontend request must see 429 with the retry hint in BOTH the
        # JSON body and the standard Retry-After header.
        in_flight = router.submit(_obs(1, seed=0))
        time.sleep(0.05)  # the worker picks it up and blocks
        queued = router.submit(_obs(1, seed=1))
        try:
            _post(frontend.url, {"obs": _obs(1, seed=2).tolist()})
            raise AssertionError("expected 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429
            payload = json.loads(e.read())
            assert payload["error"] == "backpressure"
            assert payload["retry_after_s"] > 0.0
            assert int(e.headers["Retry-After"]) >= 1
            # Error bodies are correlatable: the 429 carries the trace
            # ID (minted server-side here — no header was sent) in both
            # the body and the echoed header.
            assert payload["trace_id"]
            assert e.headers["X-Trace-Id"] == payload["trace_id"]
        for fut in (in_flight, queued):
            assert fut.result(timeout=30).actions.shape == (1, 2)
        # Whole fleet broken -> health 503 and act 503.
        router._break(router.replicas[0], "test")
        try:
            urllib.request.urlopen(
                frontend.url + "/v1/health", timeout=10
            )
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        try:
            _post(frontend.url, {"obs": _obs(1, seed=3).tolist()})
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503


def test_frontend_concurrent_clients_consistent_answers():
    """ThreadingHTTPServer + router + 2 replicas under concurrent HTTP
    clients: every response carries the same deterministic actions for
    the same observation, whichever replica answered."""
    policy = _make_policy()
    router = FleetRouter(policy, num_replicas=2, buckets=(1, 8))
    warmup_fleet(router, (OBS_DIM,))
    obs = _obs(2, seed=3)
    ref, _ = policy.predict(obs, deterministic=True)
    errors = []
    replicas_seen = set()

    def worker():
        try:
            for _ in range(5):
                body = _post(frontend.url, {"obs": obs.tolist()})
                np.testing.assert_allclose(
                    np.asarray(body["actions"], np.float32), ref,
                    rtol=1e-5, atol=1e-6,
                )
                replicas_seen.add(body["replica"])
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    with router, FleetFrontend(router, port=0) as frontend:
        threads = [
            threading.Thread(target=worker, daemon=True) for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    assert not errors, errors
    assert replicas_seen <= {0, 1}


# ---------------------------------------------------------------------------
# Trace-ID propagation (obs/): frontend -> router -> scheduler batch span
# ---------------------------------------------------------------------------


def _post_traced(url, payload, trace_id=None, timeout=30):
    """POST /v1/act returning (body, echoed X-Trace-Id header)."""
    headers = {"Content-Type": "application/json"}
    if trace_id is not None:
        headers["X-Trace-Id"] = trace_id
    req = urllib.request.Request(
        url + "/v1/act",
        data=json.dumps(payload).encode(),
        headers=headers,
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read()), resp.headers.get("X-Trace-Id")


def test_trace_id_propagates_frontend_to_batch_span():
    """ONE ID correlates a request across every layer: the header a
    client sends comes back on its own response (concurrent requests
    keep DISTINCT ids — no cross-talk through the coalescing batcher),
    a header-less request gets a minted ID, and the scheduler's
    ``serve.batch`` spans link the coalesced requests' trace IDs so the
    dispatch that served a request is findable by its ID."""
    from marl_distributedformation_tpu.obs import Tracer, set_tracer

    tracer = Tracer(ring_size=1024)
    previous = set_tracer(tracer)
    try:
        policy = _make_policy()
        router = FleetRouter(policy, num_replicas=2, buckets=(1, 8))
        warmup_fleet(router, (OBS_DIM,))
        sent_ids = [f"client-req-{i}" for i in range(8)]
        echoes = {}
        errors = []

        def worker(tid):
            try:
                body, header = _post_traced(
                    frontend.url, {"obs": _obs(2, seed=1).tolist()},
                    trace_id=tid,
                )
                echoes[tid] = (body["trace_id"], header)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        with router, FleetFrontend(router, port=0) as frontend:
            threads = [
                threading.Thread(target=worker, args=(tid,), daemon=True)
                for tid in sent_ids
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errors, errors
            # Every concurrent request got ITS OWN id back, body+header.
            assert echoes == {tid: (tid, tid) for tid in sent_ids}
            # No header -> the frontend mints one and still echoes it.
            body, header = _post_traced(
                frontend.url, {"obs": _obs(1, seed=2).tolist()}
            )
            assert body["trace_id"] and header == body["trace_id"]
            assert body["trace_id"] not in sent_ids
            # An unusable header is re-minted, not parroted back.
            weird, _ = _post_traced(
                frontend.url, {"obs": _obs(1, seed=3).tolist()},
                trace_id='evil"id',
            )
            assert weird["trace_id"] != 'evil"id'
        # The batch spans LINK the request ids: every sent id appears in
        # some dispatch's linked set, and ids never bleed into spans
        # that did not serve them more than once each.
        batch_spans = [
            r
            for r in tracer.snapshot()
            if r["kind"] == "span" and r["name"] == "serve.batch"
        ]
        assert batch_spans, "no serve.batch spans recorded"
        linked = [
            tid
            for span in batch_spans
            for tid in span["attrs"].get("trace_ids", ())
        ]
        assert set(sent_ids) <= set(linked)
        for tid in sent_ids:
            assert linked.count(tid) == 1, f"{tid} served twice?"
        # And batch spans carry the dispatch facts a timeline needs.
        for span in batch_spans:
            assert span["attrs"]["rows"] >= 1
            assert span["attrs"]["model_step"] == 0
    finally:
        set_tracer(previous)
