"""Mesh tier contract (tier-1): the cross-host serving invariants.

The fleet-of-fleets acceptance pins (serving/mesh/, docs/mesh.md),
exercised two ways:

- **in-process loopback hosts** (threads, real HTTP/RPC between them)
  for the control-plane logic: RPC taxonomy, gossip suspect->dead
  timing, stale-host quarantine + catch-up, drain-aware meta routing,
  the global barrier's monotonicity witness, wedged-host abort with
  every host restored, and trace-ID propagation through the extra hop;
- **one real 2-host SUBPROCESS e2e** (each host its own interpreter and
  XLA backend) for what threads cannot fake: ``model_step`` globally
  monotonic in response completion order across hosts through a
  coordinator-driven swap, and a real ``kill -9`` losing zero accepted
  requests.
"""

import json
import threading
import time
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marl_distributedformation_tpu.chaos import (  # noqa: E402
    FaultSchedule,
    FaultSpec,
    check_step_monotonic,
    get_fault_plane,
)
from marl_distributedformation_tpu.compat.policy import (  # noqa: E402
    LoadedPolicy,
)
from marl_distributedformation_tpu.models import MLPActorCritic  # noqa: E402
from marl_distributedformation_tpu.serving import ServingClient  # noqa: E402
from marl_distributedformation_tpu.serving.mesh import (  # noqa: E402
    HOST_ALIVE,
    HOST_DEAD,
    HOST_SUSPECT,
    HostAgent,
    JsonRpcServer,
    MeshCoordinator,
    MeshFrontend,
    MeshRpcError,
    MeshUnreachable,
    MetaRouter,
    NoHealthyHosts,
    build_inprocess_host,
    rpc_call,
    spawn_local_mesh,
)
from marl_distributedformation_tpu.utils.checkpoint import (  # noqa: E402
    save_checkpoint,
)

OBS_DIM = 6
HIDDEN = (8, 8)


def _make_policy(seed=0):
    model = MLPActorCritic(act_dim=2, hidden=HIDDEN)
    variables = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, OBS_DIM))
    )
    return LoadedPolicy(dict(variables), model_kwargs={"hidden": HIDDEN})


def _write_ckpt(log_dir, step, policy):
    return save_checkpoint(
        Path(log_dir),
        step,
        {
            "policy": type(policy.model).__name__,
            "params": policy.params,
            "num_timesteps": step,
        },
    )


def _obs(n=1):
    return np.zeros((n, OBS_DIM), np.float32)


# ---------------------------------------------------------------------------
# RPC substrate
# ---------------------------------------------------------------------------


def test_rpc_roundtrip_and_error_taxonomy():
    """The one transport primitive: 200 -> payload, handler exception ->
    typed MeshRpcError (with the exception type, no traceback), unknown
    method -> 404, nobody listening -> MeshUnreachable (the host-death
    signal everything keys on)."""
    server = JsonRpcServer(
        {
            "echo": lambda p: {"got": p},
            "boom": lambda p: (_ for _ in ()).throw(KeyError("nope")),
        }
    ).start()
    try:
        reply = rpc_call(server.url, "echo", {"x": 1})
        assert reply == {"got": {"x": 1}}
        with pytest.raises(MeshRpcError) as err:
            rpc_call(server.url, "boom", {})
        assert err.value.status == 500
        assert err.value.error_type == "KeyError"
        with pytest.raises(MeshRpcError) as err:
            rpc_call(server.url, "nosuch", {})
        assert err.value.status == 404
        dead_port = server.port  # reuse after close: nobody listens
    finally:
        server.stop()
    with pytest.raises(MeshUnreachable):
        rpc_call(f"http://127.0.0.1:{dead_port}", "echo", {}, timeout_s=1.0)


# ---------------------------------------------------------------------------
# Gossip: lease taxonomy, quarantine, catch-up
# ---------------------------------------------------------------------------


def test_gossip_suspect_to_dead_timing_and_revival():
    """The health taxonomy over real heartbeat RPCs: a silent host
    walks alive -> suspect -> dead on the lease clock, and a fresh
    heartbeat revives it."""
    coord = MeshCoordinator(lease_s=0.25, dead_after_s=0.25).serve()
    try:
        reply = rpc_call(
            coord.url,
            "mesh.register",
            {
                "host_id": "h0",
                "control_url": "http://127.0.0.1:1",
                "data_url": "http://127.0.0.1:2",
                "step": 100,
            },
        )
        assert reply["registered"] and reply["lease_s"] == 0.25

        def state():
            return coord.hosts()[0]["state"]

        assert state() == HOST_ALIVE
        deadline = time.monotonic() + 5.0
        while state() == HOST_ALIVE and time.monotonic() < deadline:
            time.sleep(0.02)
        assert state() == HOST_SUSPECT  # lease missed, not yet dead
        while state() == HOST_SUSPECT and time.monotonic() < deadline:
            time.sleep(0.02)
        assert state() == HOST_DEAD
        assert coord.routable_hosts() == []
        # Revival: one heartbeat brings it back.
        reply = rpc_call(
            coord.url, "mesh.heartbeat", {"host_id": "h0", "step": 100}
        )
        assert reply["registered"]
        assert state() == HOST_ALIVE
        # An unknown host is told to re-register, not silently gossip.
        assert rpc_call(
            coord.url, "mesh.heartbeat", {"host_id": "ghost"}
        ) == {"registered": False}
    finally:
        coord.stop()


def test_sweep_emits_death_incident_outside_hosts_lock():
    """Regression: the ``mesh_host_dead`` incident dump (tracer ring
    lock + a flight-recorder file write) must run AFTER ``_hosts_lock``
    is released — it used to fire from inside the sweep's host walk,
    nesting the tracer's lock (and its IO) under the lock every
    heartbeat RPC dispatches through. The dead_reason verdict write
    itself stays under the lock."""
    from marl_distributedformation_tpu.obs import get_tracer

    coord = MeshCoordinator(lease_s=0.01, dead_after_s=0.01)
    coord._rpc_register(
        {
            "host_id": "h0",
            "control_url": "http://127.0.0.1:1",
            "data_url": "http://127.0.0.1:2",
            "step": 100,
        }
    )
    time.sleep(0.05)  # walk h0 past suspect into dead
    tracer = get_tracer()
    lock_states = []
    original = tracer.incident

    def spy(name, **fields):
        if name == "mesh_host_dead":
            lock_states.append(coord._hosts_lock.locked())
        return original(name, **fields)

    tracer.incident = spy
    try:
        coord.sweep()
    finally:
        tracer.incident = original
    assert lock_states == [False], (
        "the death incident must be emitted after the host-table lock "
        f"is released: {lock_states}"
    )
    # The verdict itself landed (written under the lock, once).
    assert "lease expired" in coord.hosts()[0]["dead_reason"]


def test_stale_host_quarantined_until_caught_up():
    """A host serving BEHIND the mesh step must be unroutable (routing
    to it would serve an old model_step after newer responses) until
    its heartbeat reports the mesh step again."""
    coord = MeshCoordinator(lease_s=5.0, dead_after_s=5.0).serve()
    try:
        rpc_call(
            coord.url,
            "mesh.register",
            {
                "host_id": "h0",
                "control_url": "http://127.0.0.1:1",
                "data_url": "http://127.0.0.1:2",
                "step": 100,
            },
        )
        assert [h.host_id for h in coord.routable_hosts()] == ["h0"]
        coord._mesh_step = 200  # a commit this host missed
        assert coord.routable_hosts() == []
        reply = rpc_call(
            coord.url, "mesh.heartbeat", {"host_id": "h0", "step": 200}
        )
        assert reply["mesh_step"] == 200
        assert [h.host_id for h in coord.routable_hosts()] == ["h0"]
    finally:
        coord.stop()


# ---------------------------------------------------------------------------
# In-process loopback hosts (threads, real HTTP/RPC)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh2(tmp_path_factory):
    """Coordinator + 2 in-process hosts + MetaRouter over a promoted
    directory seeded at step 100. Swap tests publish ascending steps
    relative to the CURRENT mesh step, so test order never matters."""
    promoted = tmp_path_factory.mktemp("mesh_promoted")
    policy = _make_policy()
    _write_ckpt(promoted, 100, policy)
    coord = MeshCoordinator(
        log_dir=promoted, lease_s=2.0, dead_after_s=2.0,
        prepare_timeout_s=10.0,
    ).serve()
    stacks = [
        build_inprocess_host(
            promoted,
            coord.url,
            f"host{i}",
            obs_dim=OBS_DIM,
            buckets=(1,),
            heartbeat_s=0.1,
        )
        for i in range(2)
    ]
    for _, _, _, agent in stacks:
        assert agent.wait_registered(15.0)
    router = MetaRouter(coord, probe_interval_s=0.3)
    yield {
        "coord": coord,
        "router": router,
        "stacks": stacks,
        "promoted": promoted,
        "policy": policy,
    }
    for r, _, fe, agent in stacks:
        agent.stop()
        fe.stop()
        r.stop()
    coord.stop()


def test_meta_router_serves_and_routes_by_gossiped_drain(mesh2):
    router, coord = mesh2["router"], mesh2["coord"]
    result = router.predict(_obs())
    assert result.host in ("host0", "host1")
    assert result.replica >= 0
    # Routing follows the gossip: a host advertising a deep backlog
    # must lose the next request to its idle peer.
    busy = result.host
    idle = "host1" if busy == "host0" else "host0"
    with coord._hosts_lock:
        coord._hosts[busy].metrics = {"fleet_estimated_drain_s": 9.0}
        coord._hosts[idle].metrics = {"fleet_estimated_drain_s": 0.0}
    assert router.predict(_obs()).host == idle
    # The next real heartbeat restores honest gossip (both idle).
    time.sleep(0.3)
    snap = router.snapshot()
    assert snap["mesh_hosts"] == 2.0
    assert snap["mesh_routed_total"] >= 2.0


def test_global_swap_is_monotonic_in_completion_order(mesh2):
    """The tentpole invariant, in-process edition: responses completed
    across a coordinator-driven two-phase swap never carry a step going
    backward, and the commit lands on EVERY host (host_count == 2)."""
    router, coord = mesh2["router"], mesh2["coord"]
    promoted, policy = mesh2["promoted"], mesh2["policy"]
    witness = []
    lock = threading.Lock()
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                r = router.predict(_obs(), timeout_s=5.0)
            except Exception:  # noqa: BLE001 — typed errors are fine here
                continue
            with lock:
                witness.append((time.perf_counter(), r.model_step))

    threads = [
        threading.Thread(target=hammer, daemon=True) for _ in range(3)
    ]
    for t in threads:
        t.start()
    try:
        time.sleep(0.3)
        new_step = coord.fleet_step + 100
        _write_ckpt(promoted, new_step, policy)
        assert coord.refresh() is True
        assert coord.fleet_step == new_step
        assert coord.last_commit["host_count"] == 2
        assert coord.last_commit["commit_round"] >= 1
        # Post-commit responses must all carry the new step.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if router.predict(_obs()).model_step == new_step:
                break
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
    with lock:
        assert check_step_monotonic(witness) == []
        assert witness and max(s for _, s in witness) == new_step
    # Both hosts serve the new step (no torn mesh).
    for _, fleet, _, _ in mesh2["stacks"]:
        assert fleet.fleet_step == new_step


def test_trace_id_through_the_extra_hop(mesh2):
    """One X-Trace-Id survives client -> MeshFrontend -> MetaRouter ->
    host frontend and comes back on every layer's response."""
    router = mesh2["router"]
    # Programmatic: the MeshResult carries the host frontend's echo.
    result = router.predict(_obs(), trace_id="mesh-trace-42")
    assert result.trace_id == "mesh-trace-42"
    # HTTP: the meta frontend echoes header AND body.
    frontend = MeshFrontend(router).start()
    try:
        req = urllib.request.Request(
            frontend.url + "/v1/act",
            data=json.dumps({"obs": _obs().tolist()}).encode(),
            headers={
                "Content-Type": "application/json",
                "X-Trace-Id": "mesh-trace-43",
            },
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.headers.get("X-Trace-Id") == "mesh-trace-43"
            body = json.loads(resp.read())
        assert body["trace_id"] == "mesh-trace-43"
        assert body["host"] in ("host0", "host1")
        assert body["model_step"] == mesh2["coord"].fleet_step
    finally:
        frontend.stop()


def test_serving_client_endpoint_failover(mesh2):
    """The client-side satellite: a dead frontend in the endpoint list
    costs ONE attempt of the shared retry budget, not the whole budget
    burned on one address."""
    live = [fe.url for _, _, fe, _ in mesh2["stacks"]]
    dead = "http://127.0.0.1:1"  # port 1: connection refused
    client = ServingClient(
        [dead] + live, max_retries=2, backoff_base_s=0.001
    )
    actions, step = client.predict(_obs())
    assert actions.shape == (1, 2)
    assert step == mesh2["coord"].fleet_step
    # All endpoints dead: the budget caps the damage with a typed error.
    client = ServingClient(
        [dead, dead], max_retries=1, backoff_base_s=0.001
    )
    with pytest.raises(ConnectionError):
        client.predict(_obs())


def test_catch_up_after_missed_commit(mesh2):
    """A host that misses a commit round (agent down during the swap)
    is quarantined from routing on revival and catches up from the
    heartbeat's advertised checkpoint — never serving a stale step
    into the routable pool."""
    coord = mesh2["coord"]
    promoted, policy = mesh2["promoted"], mesh2["policy"]
    router_b, fleet_b, frontend_b, agent_b = mesh2["stacks"][1]
    # Take host1's agent down (its data plane keeps serving).
    agent_b.stop(deregister=True)
    new_step = coord.fleet_step + 100
    _write_ckpt(promoted, new_step, policy)
    assert coord.refresh() is True  # commits on host0 alone
    assert coord.last_commit["host_count"] == 1
    assert fleet_b.fleet_step < new_step  # host1 missed it
    # Revive host1's control plane: it registers with its stale step,
    # is quarantined, then catches up from the heartbeat reply.
    agent_new = HostAgent(
        host_id="host1",
        router=router_b,
        fleet=fleet_b,
        coordinator_url=coord.url,
        data_url=frontend_b.url,
        heartbeat_interval_s=0.1,
    ).start()
    mesh2["stacks"][1] = (router_b, fleet_b, frontend_b, agent_new)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            routable = {h.host_id for h in coord.routable_hosts()}
            if (
                "host1" in routable
                and fleet_b.fleet_step == new_step
                and agent_new.catch_ups >= 1
            ):
                break
            time.sleep(0.05)
        assert fleet_b.fleet_step == new_step
        assert "host1" in {h.host_id for h in coord.routable_hosts()}
        assert agent_new.catch_ups >= 1
    finally:
        pass  # module teardown stops the replacement agent


def test_wedged_host_barrier_abort_restores_every_host(mesh2):
    """A host wedged mid-prepare (chaos plane, mesh.prepare wedge past
    the coordinator's timeout) aborts the WHOLE round: no host commits,
    every host keeps serving the old step with gates open, and a later
    retry lands the swap — the cross-host restatement of the fleet's
    wedged-barrier abort."""
    coord = mesh2["coord"]
    router = mesh2["router"]
    promoted, policy = mesh2["promoted"], mesh2["policy"]
    old_step = coord.fleet_step
    plane = get_fault_plane()
    plane.reset()
    plane.arm(
        FaultSchedule(
            [FaultSpec("mesh.prepare", "wedge", at_hit=1, seconds=2.5)]
        )
    )
    plane.enabled = True
    coord.prepare_timeout_s, saved_timeout = 1.0, coord.prepare_timeout_s
    try:
        new_step = old_step + 100
        path = _write_ckpt(promoted, new_step, policy)
        assert coord.global_reload(path) is False  # round aborted
        assert coord.fleet_step == old_step
        assert any(
            "abort" in reason for _, reason in coord.load_errors
        )
        # Every host restored: still serving, still on the old step.
        for _, fleet, _, _ in mesh2["stacks"]:
            assert fleet.fleet_step == old_step
        assert router.predict(_obs()).model_step == old_step
        # The wedge drains; the retry (possibly twice: the first retry
        # clears a stale staged round left by the late-finishing
        # wedged prepare) must land on every host.
        plane.enabled = False
        time.sleep(2.0)
        deadline = time.monotonic() + 15.0
        landed = False
        while time.monotonic() < deadline and not landed:
            landed = coord.global_reload(path)
            if not landed:
                time.sleep(0.2)
        assert landed, f"retry never landed: {list(coord.load_errors)}"
        for _, fleet, _, _ in mesh2["stacks"]:
            assert fleet.fleet_step == new_step
    finally:
        plane.enabled = False
        plane.reset()
        coord.prepare_timeout_s = saved_timeout


def test_commit_retry_is_idempotent_and_already_at_step_short_circuits(
    tmp_path,
):
    """Two lost-ack recovery paths on the barrier's host side: a commit
    RPC retried after its response was lost must report what the first
    delivery did (not refuse a round the host already landed), and a
    prepare targeting the step the host ALREADY serves answers
    ``already_at_step`` so the coordinator counts it committed instead
    of aborting the round."""
    policy = _make_policy()
    _write_ckpt(tmp_path, 100, policy)
    coord = MeshCoordinator(lease_s=5.0, dead_after_s=5.0).serve()
    router, fleet, frontend, agent = build_inprocess_host(
        tmp_path, coord.url, "h0", obs_dim=OBS_DIM, buckets=(1,)
    )
    try:
        path = _write_ckpt(tmp_path, 150, policy)
        resp = rpc_call(
            agent.control_url,
            "mesh.prepare",
            {"round": 7, "path": str(path), "step": 150, "ttl_s": 30.0},
        )
        assert resp["staged"] is True
        first = rpc_call(agent.control_url, "mesh.commit", {"round": 7})
        assert first == {"ok": True, "step": 150}
        # The retry (lost ack) must echo the landed result, not refuse.
        retry = rpc_call(agent.control_url, "mesh.commit", {"round": 7})
        assert retry == {"ok": True, "step": 150}
        assert fleet.fleet_step == 150
        # A later round targeting the already-served step short-circuits.
        resp = rpc_call(
            agent.control_url,
            "mesh.prepare",
            {"round": 8, "path": str(path), "step": 150, "ttl_s": 30.0},
        )
        assert resp["already_at_step"] is True and not resp["staged"]
        # And the host never paused: it still serves.
        assert router.submit(_obs()).result(timeout=10.0).model_step == 150
    finally:
        agent.stop()
        frontend.stop()
        router.stop()
        coord.stop()


def test_no_routable_hosts_is_typed():
    """An empty mesh is DOWN, not busy — the taxonomy the frontend
    maps to 503."""
    coord = MeshCoordinator().serve()
    try:
        router = MetaRouter(coord)
        with pytest.raises(NoHealthyHosts):
            router.predict(_obs())
    finally:
        coord.stop()


# ---------------------------------------------------------------------------
# The real thing: 2 host subprocesses, kill -9, global monotonicity
# ---------------------------------------------------------------------------


def test_two_host_subprocess_e2e_swap_and_kill(tmp_path):
    """THE acceptance e2e: a loopback 2-host mesh of real subprocesses
    — model_step globally monotonic in response completion order
    through a coordinator-driven swap, then a real ``kill -9`` of one
    host loses zero accepted requests, the survivor absorbs the
    traffic, and the lease taxonomy declares the corpse dead."""
    policy = _make_policy()
    _write_ckpt(tmp_path, 100, policy)
    mesh = spawn_local_mesh(
        tmp_path,
        hosts=2,
        buckets=(1,),
        obs_dim=OBS_DIM,
        heartbeat_s=0.15,
        lease_s=0.6,
        dead_after_s=0.6,
        probe_interval_s=0.3,
    )
    witness = []
    outcomes = {"ok": 0, "typed": 0, "lost": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                r = mesh.router.predict(_obs(), timeout_s=5.0)
            except (
                NoHealthyHosts,
                RuntimeError,
                OSError,
                TimeoutError,
            ):
                with lock:
                    outcomes["typed"] += 1
                time.sleep(0.01)
                continue
            except BaseException:
                with lock:
                    outcomes["lost"] += 1
                continue
            with lock:
                outcomes["ok"] += 1
                witness.append((time.perf_counter(), r.model_step))

    threads = [
        threading.Thread(target=hammer, daemon=True) for _ in range(3)
    ]
    try:
        for t in threads:
            t.start()
        time.sleep(0.4)
        # Coordinator-driven global swap under load.
        path = _write_ckpt(tmp_path, 200, policy)
        assert mesh.coordinator.global_reload(path) is True
        assert mesh.coordinator.last_commit == {
            "commit_round": 1,
            "host_count": 2,
            "step": 200,
        }
        time.sleep(0.4)
        # The hammer: a REAL SIGKILL mid-load.
        killed = mesh.kill_host(0)
        time.sleep(1.5)
        # The survivor serves; the corpse is declared dead.
        post_kill = mesh.router.predict(_obs(), timeout_s=5.0)
        assert post_kill.model_step == 200
        states = {
            h["host_id"]: h["state"] for h in mesh.coordinator.hosts()
        }
        assert states[killed] == HOST_DEAD
        # A swap with one host dead still commits (host_count == 1).
        path = _write_ckpt(tmp_path, 300, policy)
        assert mesh.coordinator.global_reload(path) is True
        assert mesh.coordinator.last_commit["host_count"] == 1
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if mesh.router.predict(_obs(), timeout_s=5.0).model_step == 300:
                break
        time.sleep(0.3)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15.0)
        receipts = mesh.router.host_compile_counts()
        mesh.stop()
    for t in threads:
        assert not t.is_alive(), "a client thread wedged inside a request"
    with lock:
        assert outcomes["lost"] == 0, outcomes
        assert outcomes["ok"] > 0
        assert check_step_monotonic(witness) == []
        assert max(s for _, s in witness) == 300
    # Budget-1 receipts per surviving host.
    assert receipts, "no host answered the receipts scrape"
    for host_id, per_rung in receipts.items():
        for rung, count in per_rung.items():
            assert count <= 1.0, (host_id, rung, count)
