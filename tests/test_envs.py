"""The envs/ subsystem contract (tier-1, CPU).

The acceptance pins from the envs ISSUE:

- the registry fails fast on unknown names (did-you-mean + full listing),
  refuses silent overwrites, and keeps ``spec_for_params`` unambiguous
  (one params class per env, MRO dispatch for subclasses);
- the formation env behind the registry is the legacy ``env/formation.py``
  BITWISE — the spec's functions ARE the legacy functions, a registry-
  routed rollout reproduces the direct one exactly, and the declared
  layout matches the hard-coded column knowledge scenarios/ used to carry;
- pursuit-evasion trains end to end (Anakin fused AND Sebulba lockstep,
  fused == host loop bitwise), evaluates/gates through the budget-1
  MatrixProgram, and serves through the bucketed rung ladder with one
  compile per (env, rung);
- every registered scenario layer at severity 0 is bitwise identity on
  BOTH envs, and the obstacle layers really occlude / really move.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

# Force the threefry-partitionable flag BEFORE any draws: the knn path
# lazily imports jax_compat (which flips it), and bitwise-identity tests
# must not compare streams drawn on both sides of that flip.
from marl_distributedformation_tpu import jax_compat  # noqa: F401
from marl_distributedformation_tpu import envs
from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env import formation as legacy
from marl_distributedformation_tpu.envs import (
    FORMATION_SPEC,
    PURSUIT_SPEC,
    EnvSpec,
    ObsLayout,
    PursuitParams,
    formation_obs_layout,
    get_env,
    register_env,
    registered_envs,
    spec_for_params,
)
from marl_distributedformation_tpu.envs.pursuit import (
    pursuer_update,
    pursuit_reward,
)
from marl_distributedformation_tpu.scenarios import (
    broadcast_params,
    get_scenario,
    registered_scenarios,
    scenario_step_batch,
)
from marl_distributedformation_tpu.train import TrainConfig, Trainer
from marl_distributedformation_tpu.utils.checkpoint import checkpoint_step

PPO = PPOConfig(n_steps=4, batch_size=24, n_epochs=2)
PURSUIT = PursuitParams(num_agents=3, max_steps=20)
M = 3


@dataclasses.dataclass(frozen=True)
class _DerivedPursuit(PursuitParams):
    """A params subclass with NO registration of its own — must resolve
    to its nearest registered ancestor (pursuit_evasion), not formation."""


# ---------------------------------------------------------------------------
# Registry: fail-fast taxonomy
# ---------------------------------------------------------------------------


def test_registry_lists_both_envs_in_registration_order():
    assert registered_envs() == ("formation", "pursuit_evasion")
    assert envs.get is get_env  # the canonical spelling


def test_unknown_env_fails_fast_with_did_you_mean_and_listing():
    with pytest.raises(ValueError) as e:
        get_env("pursuit_evsion")
    msg = str(e.value)
    assert "did you mean 'pursuit_evasion'" in msg
    for name in registered_envs():
        assert name in msg, "the error must list every valid entry"
    # A name nothing close to: no hint, but still the full listing.
    with pytest.raises(ValueError, match="registered environments"):
        get_env("atari")


def test_register_refuses_silent_name_overwrite():
    with pytest.raises(ValueError, match="already registered"):
        register_env(FORMATION_SPEC)
    # Opt-in overwrite with the same spec is a no-op (and restores the
    # registry to exactly the shipped state for the rest of the session).
    register_env(FORMATION_SPEC, overwrite=True)
    assert get_env("formation") is FORMATION_SPEC
    assert spec_for_params(EnvParams(num_agents=3)) is FORMATION_SPEC


def test_register_refuses_ambiguous_params_class_claim():
    """Two envs sharing one params type would make spec_for_params
    ambiguous — the registry rejects the claim at registration time."""
    pretender = dataclasses.replace(FORMATION_SPEC, name="formation_two")
    with pytest.raises(ValueError, match="already claimed"):
        register_env(pretender)
    assert "formation_two" not in registered_envs()


def test_spec_for_params_dispatches_on_most_derived_type():
    assert spec_for_params(EnvParams(num_agents=3)) is FORMATION_SPEC
    assert spec_for_params(PURSUIT) is PURSUIT_SPEC
    # MRO walk: an unregistered subclass resolves to its registered base.
    assert spec_for_params(_DerivedPursuit(num_agents=3)) is PURSUIT_SPEC


def test_spec_for_params_unregistered_type_fails_naming_pairs():
    with pytest.raises(ValueError) as e:
        spec_for_params(object())
    msg = str(e.value)
    assert "no registered environment" in msg
    assert "formation (EnvParams)" in msg
    assert "pursuit_evasion (PursuitParams)" in msg


# ---------------------------------------------------------------------------
# ObsLayout: declared blocks + fail-fast require
# ---------------------------------------------------------------------------


def test_formation_layout_matches_the_obs_row_geometry():
    params = EnvParams(num_agents=3)
    layout = formation_obs_layout(params)
    assert layout.dim == params.obs_dim
    assert layout.topology == "ring"
    assert layout.names() == ("self", "neighbor", "goal")
    # The mask covers the whole row exactly once (blocks partition it).
    assert layout.columns(*layout.names()).all()
    # goal_in_obs=False drops the goal block, not just its columns.
    bare = formation_obs_layout(EnvParams(num_agents=3, goal_in_obs=False))
    assert bare.block("goal") is None


def test_knn_neighbor_block_is_disjoint_ranges():
    params = EnvParams(num_agents=5, obs_mode="knn", knn_k=2)
    layout = formation_obs_layout(params)
    assert layout.topology == "knn"
    ranges = layout.require("neighbor")
    assert len(ranges) == 2, "offsets+distances block AND the index block"
    from marl_distributedformation_tpu.scenarios import neighbor_obs_columns

    np.testing.assert_array_equal(
        layout.columns("neighbor"), neighbor_obs_columns(params)
    )


def test_pursuit_layout_renames_goal_to_pursuer_and_require_fails_fast():
    layout = PURSUIT_SPEC.obs_layout(PURSUIT)
    assert layout.names() == ("self", "neighbor", "pursuer")
    # Same column geometry as formation — only the block NAME differs,
    # so a layer wanting "goal" fails fast instead of silently masking.
    assert layout.require("pursuer") == formation_obs_layout(
        EnvParams(num_agents=3)
    ).require("goal")
    with pytest.raises(ValueError) as e:
        layout.require("goal", needed_by="moving-goal layer")
    msg = str(e.value)
    assert "moving-goal layer" in msg and "pursuer" in msg


def test_obs_layout_rejects_out_of_range_blocks():
    with pytest.raises(AssertionError):
        ObsLayout(dim=4, topology="ring", blocks=(("self", ((0, 5),)),))
    with pytest.raises(AssertionError):
        ObsLayout(dim=4, topology="grid", blocks=())


# ---------------------------------------------------------------------------
# Formation behind the registry == legacy env/formation.py, bitwise
# ---------------------------------------------------------------------------


def test_formation_spec_functions_are_the_legacy_functions():
    """The strongest possible identity: not equal trajectories — the SAME
    function objects, so the formation path cannot drift by construction."""
    assert FORMATION_SPEC.params_cls is EnvParams
    assert FORMATION_SPEC.reset is legacy.reset
    assert FORMATION_SPEC.step is legacy.step
    assert FORMATION_SPEC.reset_batch is legacy.reset_batch
    assert FORMATION_SPEC.step_batch is legacy.step_batch


def _drive(params, reset_batch, step_batch, num_steps=6, m=M, seed=0):
    state = reset_batch(jax.random.PRNGKey(seed), params, m)
    key = jax.random.PRNGKey(7)
    rows = []
    for _ in range(num_steps):
        key, k_act = jax.random.split(key)
        vel = params.max_speed * jax.random.uniform(
            k_act, (m, params.num_agents, 2), minval=-1.0, maxval=1.0
        )
        state, tr = step_batch(state, vel, params)
        rows.append(
            jax.device_get(
                (
                    state.agents, state.goal, state.obstacles,
                    tr.obs, tr.reward, tr.done,
                )
            )
        )
    return rows


@pytest.mark.parametrize(
    "params",
    [
        EnvParams(num_agents=4, max_steps=5, num_obstacles=2),
        EnvParams(num_agents=5, max_steps=5, obs_mode="knn", knn_k=2),
    ],
    ids=["ring", "knn"],
)
def test_formation_via_registry_rollout_is_bitwise_legacy(params):
    spec = get_env("formation")
    direct = _drive(params, legacy.reset_batch, legacy.step_batch)
    routed = _drive(params, spec.reset_batch, spec.step_batch)
    for d_row, r_row in zip(direct, routed):
        for d, r in zip(d_row, r_row):
            assert np.array_equal(np.asarray(d), np.asarray(r))


def test_gym_flavored_protocol_view_matches_primitives():
    params = EnvParams(num_agents=3)
    state, obs = FORMATION_SPEC.reset_env(jax.random.PRNGKey(0), params)
    np.testing.assert_array_equal(
        np.asarray(obs), np.asarray(FORMATION_SPEC.obs(state, params))
    )
    vel = jnp.zeros((params.num_agents, 2), jnp.float32)
    nxt, obs2, reward, done, info = FORMATION_SPEC.step_env(
        state, vel, params
    )
    assert obs2.shape == obs.shape
    assert reward.shape == (params.num_agents,)
    assert "avg_dist_to_goal" in info
    assert FORMATION_SPEC.default_params(num_agents=7).num_agents == 7


# ---------------------------------------------------------------------------
# Pursuit-evasion: scripted pursuer physics
# ---------------------------------------------------------------------------


def test_pursuer_chases_nearest_evader_without_overshoot():
    params = PursuitParams(num_agents=3, pursuer_speed=7.0)
    agents = jnp.array(
        [[100.0, 100.0], [400.0, 400.0], [500.0, 100.0]], jnp.float32
    )
    # Far gap: moves exactly pursuer_speed toward the NEAREST evader.
    moved = pursuer_update(agents, jnp.array([100.0, 50.0]), params)
    np.testing.assert_allclose(
        np.asarray(moved), [100.0, 57.0], atol=1e-5
    )
    # Gap below pursuer_speed: lands ON the evader, never past it.
    close = pursuer_update(agents, jnp.array([100.0, 98.0]), params)
    np.testing.assert_allclose(np.asarray(close), [100.0, 100.0], atol=1e-5)


def test_capture_penalty_applies_inside_capture_radius_only():
    params = PursuitParams(num_agents=3)
    pursuer = jnp.array([100.0, 100.0], jnp.float32)
    agents = jnp.array(
        [[100.0, 110.0], [400.0, 400.0], [600.0, 300.0]], jnp.float32
    )  # agent 0 within capture_radius=30, the others far
    zeros = jnp.zeros((3,), jnp.float32)
    _, terms = pursuit_reward(agents, pursuer, zeros, zeros, params)
    penalty = np.asarray(terms["capture_penalty"])
    assert penalty[0] == -params.capture_penalty
    assert penalty[1] == penalty[2] == 0.0
    # Fleeing pays: the far agents earn strictly more evade reward.
    evade = np.asarray(terms["evade_reward"])
    assert evade[1] > evade[0] and evade[2] > evade[0]


def test_pursuit_metrics_keys_match_formation():
    """The gate, sweeps, and bench consume metric names — both envs must
    emit the same dictionary shape (avg_dist_to_goal is distance to the
    pursuer here)."""
    from marl_distributedformation_tpu.eval import evaluate, zero_act_fn

    form = evaluate(zero_act_fn(), EnvParams(num_agents=3, max_steps=5),
                    num_formations=2)
    purs = evaluate(zero_act_fn(), PursuitParams(num_agents=3, max_steps=5),
                    num_formations=2)
    assert set(form) == set(purs)
    shared = {"episode_return_per_agent", "final_avg_dist_to_goal",
              "final_ave_dist_to_neighbor"}
    assert shared <= set(purs)
    assert all(np.isfinite(v) for v in purs.values())


# ---------------------------------------------------------------------------
# Scenario layers on BOTH envs: severity-0 bitwise identity
# ---------------------------------------------------------------------------

PURSUIT_SCEN = PursuitParams(num_agents=4, max_steps=5, num_obstacles=4)


def _scenario_step_fn(params, name, severity, m=M):
    sp = broadcast_params(get_scenario(name).build(jnp.float32(severity)), m)
    return lambda state, vel: scenario_step_batch(state, vel, sp, params)


@pytest.mark.parametrize("name", registered_scenarios())
def test_pursuit_severity_zero_is_bitwise_clean(name):
    spec = spec_for_params(PURSUIT_SCEN)
    clean = _drive(PURSUIT_SCEN, spec.reset_batch, spec.step_batch)
    scen = _drive(
        PURSUIT_SCEN,
        spec.reset_batch,
        lambda state, vel, p: _scenario_step_fn(p, name, 0.0)(state, vel),
    )
    for t, (c_row, s_row) in enumerate(zip(clean, scen)):
        for c, s in zip(c_row, s_row):
            assert np.array_equal(np.asarray(c), np.asarray(s)), (
                f"{name} severity=0 diverged from clean pursuit at step {t}"
            )


@pytest.mark.parametrize(
    "name", [n for n in registered_scenarios() if n != "clean"]
)
def test_pursuit_severity_one_perturbs(name):
    spec = spec_for_params(PURSUIT_SCEN)
    clean = _drive(PURSUIT_SCEN, spec.reset_batch, spec.step_batch)
    scen = _drive(
        PURSUIT_SCEN,
        spec.reset_batch,
        lambda state, vel, p: _scenario_step_fn(p, name, 1.0)(state, vel),
    )
    assert any(
        not np.array_equal(np.asarray(c), np.asarray(s))
        for c_row, s_row in zip(clean, scen)
        for c, s in zip(c_row, s_row)
    ), f"{name} at severity 1 must change the pursuit trajectory"


# ---------------------------------------------------------------------------
# Obstacle layers: occlusion masks declared columns, obstacles really move
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "params",
    [
        EnvParams(num_agents=4, max_steps=5, num_obstacles=6),
        PursuitParams(num_agents=4, max_steps=5, num_obstacles=6),
    ],
    ids=["formation", "pursuit"],
)
def test_obstacle_field_occludes_only_declared_neighbor_columns(params):
    spec = spec_for_params(params)
    layout = spec.obs_layout(params)
    cols = layout.columns("neighbor", needed_by="test")
    state = spec.reset_batch(jax.random.PRNGKey(0), params, 8)
    vel = jnp.zeros((8, params.num_agents, 2), jnp.float32)
    _, tr_clean = spec.step_batch(state, vel, params)
    sp = broadcast_params(
        get_scenario("obstacle_field").build(jnp.float32(1.0)), 8
    )
    assert float(np.asarray(sp.obstacle_occlusion)[0]) > 0
    _, tr = scenario_step_batch(state, vel, sp, params)
    clean_obs, obs = np.asarray(tr_clean.obs), np.asarray(tr.obs)
    # Non-neighbor columns are untouched; occluded entries are ZEROED
    # neighbor columns; and with 6 obstacles someone IS occluded.
    np.testing.assert_array_equal(obs[..., ~cols], clean_obs[..., ~cols])
    changed = obs != clean_obs
    assert changed.any(), "severity-1 occlusion never fired"
    assert np.all(obs[changed] == 0.0)
    # Physics is untouched — sensors lie, the world doesn't.
    np.testing.assert_array_equal(
        np.asarray(tr.reward), np.asarray(tr_clean.reward)
    )


def test_moving_obstacles_drift_within_speed_and_world_box():
    params = EnvParams(num_agents=4, max_steps=50, num_obstacles=4)
    spec = spec_for_params(params)
    sp = broadcast_params(
        get_scenario("moving_obstacles").build(jnp.float32(1.0)), M
    )
    speed = float(np.asarray(sp.obstacle_speed)[0])
    assert speed > 0
    state = spec.reset_batch(jax.random.PRNGKey(0), params, M)
    vel = jnp.zeros((M, params.num_agents, 2), jnp.float32)
    prev = np.asarray(state.obstacles)
    for _ in range(3):
        state, _ = scenario_step_batch(state, vel, sp, params)
        cur = np.asarray(state.obstacles)
        moved = np.linalg.norm(cur - prev, axis=-1)
        assert moved.max() > 0.0, "obstacles never moved"
        assert moved.max() <= speed + 1e-4, "moved farther than the speed"
        assert cur.min() >= 0.0
        assert cur[..., 0].max() <= params.width
        assert cur[..., 1].max() <= params.height
        prev = cur


# ---------------------------------------------------------------------------
# Pursuit trains end to end: fused == host loop, Sebulba lockstep, then
# gate + serve with budget-1 receipts (the full promotion loop)
# ---------------------------------------------------------------------------


def _pursuit_trainer(tmp_path, cls=Trainer, **overrides):
    defaults = dict(
        num_formations=4,
        checkpoint=False,
        seed=0,
        name="pursuit",
        log_dir=str(tmp_path / "logs"),
        log_interval=1,
    )
    defaults.update(overrides)
    return cls(PURSUIT, ppo=PPO, config=TrainConfig(**defaults))


def test_pursuit_fused_chunk_bitwise_matches_host_loop(tmp_path):
    """The new env inherits the fused-scan guarantee: one scanned chunk
    of K reproduces K host-loop iterations bit for bit."""
    host = _pursuit_trainer(tmp_path / "host")
    fused = _pursuit_trainer(tmp_path / "fused", fused_chunk=3)
    per_iter = [jax.device_get(host.run_iteration()) for _ in range(3)]
    stacked = jax.device_get(fused.run_chunk())
    assert host.num_timesteps == fused.num_timesteps
    for name, values in stacked.items():
        for i in range(3):
            np.testing.assert_array_equal(
                np.asarray(values[i]),
                np.asarray(per_iter[i][name]),
                err_msg=f"metric {name!r} diverges at fused iteration {i}",
            )
    for a, b in zip(
        jax.tree_util.tree_leaves(host.train_state.params),
        jax.tree_util.tree_leaves(fused.train_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert fused.retrace_guard.count == 1  # budget-1 fused program


def test_pursuit_sebulba_lockstep_matches_anakin(tmp_path):
    """Depth-1 lockstep on the NEW env drives the real transfer plumbing
    and reproduces Anakin within float tolerance. (Not bitwise like the
    formation pin: pursuit's extra reductions — argmin / vector norms in
    the scripted pursuer — fuse differently across the acting/learning
    program cut. The bitwise guarantee for pursuit lives in the fused-
    vs-host test above, where both sides run the same program shape.)"""
    from marl_distributedformation_tpu.train.sebulba import SebulbaDriver

    anakin = _pursuit_trainer(tmp_path / "anakin")
    sebulba = _pursuit_trainer(
        tmp_path / "sebulba", cls=SebulbaDriver, architecture="sebulba"
    )
    for i in range(2):
        a = jax.device_get(anakin.run_iteration())
        s = jax.device_get(sebulba.run_lockstep_iteration())
        assert set(a) == set(s)
        for name in a:
            np.testing.assert_allclose(
                np.asarray(s[name]),
                np.asarray(a[name]),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"metric {name!r} diverges at iteration {i}",
            )
    assert anakin.num_timesteps == sebulba.num_timesteps
    for a, s in zip(
        jax.tree_util.tree_leaves(
            jax.device_get(anakin.train_state.params)
        ),
        jax.tree_util.tree_leaves(
            jax.device_get(sebulba.train_state.params)
        ),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(s), rtol=1e-5, atol=1e-7
        )


def test_pursuit_full_loop_train_eval_gate_serve(tmp_path):
    """The ISSUE's end-to-end pin: fused pursuit training writes real
    checkpoints; eval restores and scores them; the PromotionGate's
    MatrixProgram judges them with ONE compile across candidates; the
    serving rung ladder compiles once per bucket (RetraceGuard budget 1
    — a second trace would raise, not just fail a count check)."""
    from marl_distributedformation_tpu.compat.policy import LoadedPolicy
    from marl_distributedformation_tpu.eval import evaluate_checkpoint
    from marl_distributedformation_tpu.pipeline import (
        GateConfig,
        PromotionGate,
    )
    from marl_distributedformation_tpu.serving import BucketedPolicyEngine

    log_dir = tmp_path / "run"
    per_iter = 4 * PURSUIT.num_agents * PPO.n_steps
    trainer = _pursuit_trainer(
        log_dir,
        checkpoint=True,
        fused_chunk=2,
        total_timesteps=4 * per_iter,
        save_freq=5,
    )
    trainer.train()
    assert trainer.retrace_guard.count == 1  # one fused program, ever
    ckpts = sorted(
        (log_dir / "logs").glob("**/rl_model_*_steps.msgpack"),
        key=checkpoint_step,
    )
    assert len(ckpts) >= 2

    # Eval restores the checkpoint against PURSUIT params (env-generic
    # dispatch inside run_episode_metrics) and scores finitely.
    scores = evaluate_checkpoint(str(ckpts[-1]), PURSUIT, num_formations=8)
    assert all(np.isfinite(v) for v in scores.values())
    assert "episode_return_per_agent" in scores

    # The gate: bootstrap candidate passes, and the SECOND candidate
    # reuses the compiled MatrixProgram (budget-1 across candidates).
    gate = PromotionGate(
        PURSUIT,
        GateConfig(
            scenarios=("wind",),
            severities=(1.0,),
            eval_formations=8,
            clean_tolerance=10.0,
            rung_tolerance=10.0,
        ),
    )
    verdict = gate.evaluate(ckpts[0])
    assert verdict.passed, verdict.reasons
    assert verdict.eval_compiles == 1
    verdict2 = gate.evaluate(ckpts[-1])
    assert verdict2.passed, verdict2.reasons
    assert gate.program.compile_count == 1

    # Serving: the promoted pursuit policy rides the bucketed ladder —
    # obs-row in, actions out, one compile per rung across a mixed
    # stream (including the above-top-rung split path).
    pol = LoadedPolicy.from_checkpoint(
        ckpts[-1], act_dim=PURSUIT.act_dim, env_params=PURSUIT
    )
    engine = BucketedPolicyEngine(
        pol, buckets=(1, 8), max_traces_per_bucket=1
    )
    rng = np.random.default_rng(0)
    for n in (1, 3, 8, 9, 1, 8):
        obs = rng.standard_normal((n, PURSUIT.obs_dim)).astype(np.float32)
        actions = engine.act(obs, deterministic=True)
        assert actions.shape == (n, PURSUIT.act_dim)
        assert np.abs(actions).max() <= 1.0 + 1e-6
    assert engine.compile_counts() == {1: 1, 8: 1}


# ---------------------------------------------------------------------------
# Config plumbing: env= selects the registered env everywhere
# ---------------------------------------------------------------------------


def test_env_key_selects_registered_params_class():
    from marl_distributedformation_tpu.utils import (
        env_params_from_config,
        load_config,
    )

    cfg = load_config([])
    assert type(env_params_from_config(cfg)) is EnvParams  # default
    cfg = load_config(["env=pursuit_evasion", "pursuer_speed=9.0"])
    params = env_params_from_config(cfg)
    assert type(params) is PursuitParams
    assert params.pursuer_speed == pytest.approx(9.0)


def test_override_validation_is_env_aware():
    from marl_distributedformation_tpu.utils.config import (
        validate_override_keys,
    )

    # Env-specific knobs validate only under the env that declares them.
    validate_override_keys(["env=pursuit_evasion", "capture_radius=25"])
    with pytest.raises(SystemExit, match="capture_radius"):
        validate_override_keys(["capture_radius=25"])
    # A mistyped env name fails with the registry's did-you-mean.
    with pytest.raises(SystemExit, match="pursuit_evasion"):
        validate_override_keys(["env=pursuit_evsion"])
