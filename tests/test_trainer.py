"""Integration tests: trainer, checkpointing, config, metrics."""

import json

import jax
import numpy as np
import pytest

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.train import TrainConfig, Trainer
from marl_distributedformation_tpu.utils import (
    apply_overrides,
    checkpoint_step,
    latest_checkpoint,
    load_config,
)


def tiny_trainer(tmp_path, **overrides):
    env_params = EnvParams(num_agents=3)
    ppo = PPOConfig(n_steps=4, batch_size=24, n_epochs=2)
    defaults = dict(
        num_formations=4,
        total_timesteps=4 * 3 * 4 * 3,  # 3 iterations
        seed=0,
        save_freq=8,
        name="test",
        log_dir=str(tmp_path / "logs"),
        log_interval=1,
    )
    defaults.update(overrides)
    return Trainer(env_params, ppo=ppo, config=TrainConfig(**defaults))


def test_trainer_runs_and_logs(tmp_path):
    trainer = tiny_trainer(tmp_path)
    final = trainer.train()
    assert trainer.num_timesteps == trainer.total_timesteps
    assert np.isfinite(final["reward"])
    assert np.isfinite(final["loss"])
    # Observability contract metric names (SURVEY.md §5).
    for name in (
        "reward",
        "avg_dist_to_goal",
        "ave_dist_to_neighbor",
        "std_dist_to_neighbor",
        "close_to_goal_reward",
        "reward_dist",
        "reward_right_neighbor",
        "reward_left_neighbor",
    ):
        assert name in final, name
    records = [
        json.loads(line)
        for line in (tmp_path / "logs" / "metrics.jsonl").read_text().splitlines()
    ]
    assert len(records) == 3
    assert records[-1]["step"] == trainer.total_timesteps


def test_iters_per_dispatch_matches_single_dispatch(tmp_path):
    """iters_per_dispatch=2 runs the same math as two single-iteration
    dispatches: params match tightly, timestep accounting and metric
    aggregation (mean; dones sum) hold, and train() end-to-end works."""
    single = tiny_trainer(tmp_path, name="single")
    burst = tiny_trainer(
        tmp_path, name="burst", iters_per_dispatch=2,
        log_dir=str(tmp_path / "logs_burst"),
    )
    m0 = single.run_iteration()
    m1 = single.run_iteration()
    mb = burst.run_iteration()
    assert single.num_timesteps == burst.num_timesteps == 2 * 4 * 4 * 3
    leaves_s = jax.tree_util.tree_leaves(single.train_state.params)
    leaves_b = jax.tree_util.tree_leaves(burst.train_state.params)
    for a, b in zip(leaves_s, leaves_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )
    np.testing.assert_allclose(
        float(mb["reward"]),
        (float(m0["reward"]) + float(m1["reward"])) / 2,
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(mb["episode_dones"]),
        float(m0["episode_dones"]) + float(m1["episode_dones"]),
    )
    # End-to-end: 4 iterations in 2 dispatches, checkpoints + logs land.
    full = tiny_trainer(
        tmp_path, name="burst_train", iters_per_dispatch=2,
        log_dir=str(tmp_path / "logs_bt"),
        total_timesteps=4 * 4 * 4 * 3,
    )
    final = full.train()
    assert full.num_timesteps == full.total_timesteps
    assert np.isfinite(final["loss"])
    assert latest_checkpoint(tmp_path / "logs_bt") is not None


def test_checkpoint_write_discovery_resume(tmp_path):
    trainer = tiny_trainer(tmp_path)
    trainer.train()
    path = latest_checkpoint(tmp_path / "logs")
    assert path is not None
    # Naming contract: rl_model_{steps}_steps.* with max-step discovery
    # (visualize_policy.py:31).
    assert "rl_model" in path.name
    assert checkpoint_step(path) == trainer.total_timesteps
    assert int(path.name.split("_")[-2].split(".")[0]) == trainer.total_timesteps

    # Resume restores params and counters exactly.
    resumed = tiny_trainer(tmp_path, resume=True)
    assert resumed.num_timesteps == trainer.total_timesteps
    a = jax.tree_util.tree_leaves(trainer.train_state.params)
    b = jax.tree_util.tree_leaves(resumed.train_state.params)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_trainer_deterministic_under_seed(tmp_path):
    t1 = tiny_trainer(tmp_path / "a", checkpoint=False)
    t2 = tiny_trainer(tmp_path / "b", checkpoint=False)
    m1 = t1.run_iteration()
    m2 = t2.run_iteration()
    np.testing.assert_allclose(
        float(m1["reward"]), float(m2["reward"]), rtol=1e-6
    )
    for x, y in zip(
        jax.tree_util.tree_leaves(t1.train_state.params),
        jax.tree_util.tree_leaves(t2.train_state.params),
    ):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_learning_improves_reward(tmp_path):
    """PPO on a small problem should beat its initial random policy —
    the cheap end-to-end learning signal (SURVEY.md §4)."""
    env_params = EnvParams(num_agents=3, strict_parity=False, max_steps=64)
    ppo = PPOConfig(n_steps=16, batch_size=192, n_epochs=4)
    trainer = Trainer(
        env_params,
        ppo=ppo,
        config=TrainConfig(
            num_formations=16,
            total_timesteps=16 * 3 * 16 * 40,  # 40 iterations
            checkpoint=False,
            name="learn",
            log_dir=str(tmp_path / "logs"),
        ),
    )
    first = trainer.run_iteration()
    rewards = []
    while trainer.num_timesteps < trainer.total_timesteps:
        rewards.append(float(trainer.run_iteration()["reward"]))
    late = np.mean(rewards[-5:])
    assert late > float(first["reward"]) + 1.0, (
        f"no learning: first={float(first['reward'])}, late={late}"
    )


def test_config_loading_and_overrides(tmp_path):
    cfg = load_config(["name=x", "num_formation=16", "learning_rate=3e-4"])
    assert cfg.name == "x"
    assert cfg.num_formation == 16
    assert cfg.learning_rate == pytest.approx(3e-4)
    assert cfg.share_reward_ratio == pytest.approx(0.25)
    apply_overrides(cfg, ["goal_in_obs=false"])
    assert cfg.goal_in_obs is False
    with pytest.raises(ValueError):
        apply_overrides(cfg, ["oops"])


def test_env_params_from_config_forwards_share_ratio():
    """Q6 fixed: share_reward_ratio flows from cfg to the env."""
    from marl_distributedformation_tpu.utils import env_params_from_config

    cfg = load_config(["share_reward_ratio=0.4", "num_agents_per_formation=7"])
    params = env_params_from_config(cfg)
    assert params.share_reward_ratio == pytest.approx(0.4)
    assert params.num_agents == 7


def test_dotted_override_under_null_key():
    cfg = load_config(["mesh.dp=4"])
    assert cfg.mesh == {"dp": 4}
    # Hydra semantics: numeric-looking values parse as ints; path users
    # must stringify (train.py does).
    cfg2 = load_config(["name=2024"])
    assert str(cfg2.name) == "2024"


@pytest.mark.slow
def test_resume_reapplies_sharding(tmp_path):
    from marl_distributedformation_tpu.parallel import make_shard_fn

    shard_fn = make_shard_fn({"dp": 8})
    t1 = tiny_trainer(tmp_path, num_formations=8, total_timesteps=8 * 3 * 4 * 2)
    t1.train()
    resumed = Trainer(
        EnvParams(num_agents=3),
        ppo=PPOConfig(n_steps=4, batch_size=24, n_epochs=2),
        config=TrainConfig(
            num_formations=8,
            name="test",
            log_dir=str(tmp_path / "logs"),
            resume=True,
        ),
        shard_fn=shard_fn,
    )
    assert not resumed.env_state.agents.sharding.is_fully_replicated


@pytest.mark.slow
def test_profile_flag_writes_trace(tmp_path):
    """profile=True captures a jax.profiler trace of post-warmup iterations
    into {log_dir}/profile/ (VERDICT.md round-1 #6)."""
    import pathlib

    trainer = tiny_trainer(
        tmp_path,
        profile=True,
        profile_iterations=2,
        total_timesteps=4 * 3 * 4 * 4,  # 4 iterations
        checkpoint=False,
    )
    trainer.train()
    profile_dir = pathlib.Path(trainer.log_dir) / "profile"
    assert profile_dir.is_dir(), "no trace directory written"
    files = list(profile_dir.rglob("*"))
    assert any(f.is_file() for f in files), "trace directory is empty"


@pytest.mark.slow
def test_profile_breakdown(tmp_path):
    trainer = tiny_trainer(tmp_path, checkpoint=False)
    bd = trainer.profile_breakdown(iters=2)
    for k in ("total", "rollout", "env", "update", "policy"):
        assert bd[k] >= 0.0, bd
    assert bd["total"] > 0.0 and bd["rollout"] > 0.0
    np.testing.assert_allclose(
        bd["frac_env"] + bd["frac_policy"] + bd["frac_update"], 1.0,
        rtol=1e-6,
    )
    # the trainer remains usable afterwards (no donated-buffer corruption)
    metrics = trainer.run_iteration()
    assert np.isfinite(float(metrics["loss"]))


def test_throughput_windowed_rate():
    import time as time_mod

    from marl_distributedformation_tpu.utils import Throughput

    meter = Throughput(window=4)
    meter.tick(100)  # warmup tick: starts the clock only
    for _ in range(10):
        time_mod.sleep(0.01)
        meter.tick(10)
    rate = meter.rate()
    # ~10 steps / 10ms = ~1000/s; generous bounds for CI jitter
    assert 200 < rate < 5000, rate


# ---------------------------------------------------------------------------
# Runtime tracing guards (analysis/guards.py, opt-in via TrainConfig)
# ---------------------------------------------------------------------------


def test_retrace_guard_train_step_compiles_exactly_once(tmp_path):
    """The steady-state contract the retrace guard enforces: the jitted
    train iteration compiles on the first dispatch and NEVER again for
    identical shapes — a second iteration triggers zero recompiles (with
    guard_retraces=1, a retrace would raise RetraceError instead of
    silently eating a multi-second compile per iteration)."""
    trainer = tiny_trainer(tmp_path, checkpoint=False, guard_retraces=1)
    trainer.run_iteration()
    assert trainer.retrace_guard.count == 1, "first dispatch = one compile"
    trainer.run_iteration()  # identical shapes: cache hit, no retrace
    assert trainer.retrace_guard.count == 1, (
        "second dispatch with identical shapes must not retrace"
    )


def test_retrace_guard_raises_past_budget():
    from marl_distributedformation_tpu.utils.profiling import (
        RetraceError,
        RetraceGuard,
    )

    guard = RetraceGuard("toy", max_traces=1)
    f = jax.jit(guard.wrap(lambda x: x * 2))
    f(np.zeros((2,), np.float32))
    f(np.ones((2,), np.float32))  # same shape: cache hit
    assert guard.count == 1
    with pytest.raises(RetraceError, match="toy"):
        f(np.zeros((3,), np.float32))  # shape drift forces a retrace
    guard.reset()
    assert guard.count == 0


def test_transfer_guard_blocks_host_sync():
    """On accelerator backends a device->host sync under the guard must
    raise; the XLA CPU backend aliases device and host memory (zero-copy
    readbacks), so there the guard is a documented no-op and this test
    pins only the clean enter/exit contract."""
    from marl_distributedformation_tpu.utils.profiling import (
        no_host_transfers,
    )

    x = jax.jit(lambda v: v + 1)(np.arange(4.0, dtype=np.float32))
    if jax.default_backend() == "cpu":
        with no_host_transfers():
            pass  # inert on CPU; must still nest/exit cleanly
    else:
        with pytest.raises(Exception, match="[Dd]isallow"):
            with no_host_transfers():
                float(x.sum())  # device->host sync must be rejected
    assert float(x.sum()) == 10.0  # guard lifts cleanly on exit


def test_guarded_trainer_iterations_are_transfer_free(tmp_path):
    """guard_transfers=true: post-warmup dispatches run under the
    device->host transfer guard — proving the hot loop never syncs."""
    trainer = tiny_trainer(
        tmp_path, checkpoint=False, guard_transfers=True, guard_nans=True
    )
    for _ in range(3):
        metrics = trainer.run_iteration()
    # metrics stay device arrays inside the loop; the (legal) sync
    # happens only here, outside the guarded region.
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_nan_guard_restores_previous_setting():
    from marl_distributedformation_tpu.utils.profiling import nan_guard

    before = jax.config.jax_debug_nans
    with nan_guard(True):
        assert jax.config.jax_debug_nans is True
        with pytest.raises(FloatingPointError):
            jnp_div = jax.jit(lambda a, b: a / b)
            jax.block_until_ready(
                jnp_div(np.float32(0.0), np.float32(0.0))
            )
    assert jax.config.jax_debug_nans == before
