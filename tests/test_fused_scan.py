"""Anakin-mode fused-scan training (TrainConfig.fused_chunk).

The contract (ISSUE 5 acceptance): K fused-scan iterations are
BITWISE-identical to K host-loop iterations at the same seed/config —
params AND per-iteration metrics — for the plain trainer, a
scenario-schedule trainer (stage change INSIDE the chunk), and the
dp-mesh trainer; the fused program compiles exactly once (budget-1
RetraceGuard); and the background checkpoint pipeline can never leave a
torn or visible half-checkpoint, even when a write crashes mid-flight.
"""

import json
import pathlib

import jax
import numpy as np
import pytest

# Bitwise PRNG-stream comparisons need partitionable threefry forced
# before any key math (see PR 3's note in CHANGES.md).
from marl_distributedformation_tpu import jax_compat  # noqa: F401
from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.scenarios.schedule import (
    ScenarioSchedule,
    ScenarioStage,
)
from marl_distributedformation_tpu.train import TrainConfig, Trainer
from marl_distributedformation_tpu.utils import (
    AsyncCheckpointWriter,
    checkpoint_path,
    latest_checkpoint,
)

PPO = PPOConfig(n_steps=4, batch_size=24, n_epochs=2)


def make_trainer(tmp_path, scenario=None, shard_fn=None, **overrides):
    defaults = dict(
        num_formations=4,
        checkpoint=False,
        seed=0,
        name="fused",
        log_dir=str(tmp_path / "logs"),
        log_interval=1,
    )
    defaults.update(overrides)
    return Trainer(
        EnvParams(num_agents=3),
        ppo=PPO,
        config=TrainConfig(**defaults),
        shard_fn=shard_fn,
        scenario_schedule=scenario,
    )


def two_stage_schedule():
    """Severity ramp + scenario-mix change that land INSIDE a chunk of 4."""
    return ScenarioSchedule(
        stages=(
            ScenarioStage(rollouts=2, scenarios=("wind",), severity=0.8),
            ScenarioStage(
                rollouts=2, scenarios=("wind", "sensor_noise"), severity=0.3
            ),
        )
    )


def assert_bitwise_parity(host, fused, k):
    """Run k host-loop iterations vs ONE fused chunk of k; params and
    every per-iteration metric must match bit for bit."""
    per_iter = [jax.device_get(host.run_iteration()) for _ in range(k)]
    stacked = jax.device_get(fused.run_chunk())
    assert host.num_timesteps == fused.num_timesteps
    for name, values in stacked.items():
        for i in range(k):
            np.testing.assert_array_equal(
                np.asarray(values[i]),
                np.asarray(per_iter[i][name]),
                err_msg=f"metric {name!r} diverges at fused iteration {i}",
            )
    for a, b in zip(
        jax.tree_util.tree_leaves(host.train_state.params),
        jax.tree_util.tree_leaves(fused.train_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Bitwise parity: fused scan == host loop (the acceptance pin)
# ---------------------------------------------------------------------------


def test_fused_scan_bitwise_matches_host_loop_plain(tmp_path):
    host = make_trainer(tmp_path / "host")
    fused = make_trainer(tmp_path / "fused", fused_chunk=3)
    assert_bitwise_parity(host, fused, 3)


def test_fused_scan_bitwise_matches_host_loop_scenario_schedule(tmp_path):
    """The chunk's scanned ScenarioParams xs reproduce the host loop's
    per-dispatch draws exactly — including a stage transition and a
    severity-ramp step in the MIDDLE of the fused chunk."""
    host = make_trainer(tmp_path / "host", scenario=two_stage_schedule())
    fused = make_trainer(
        tmp_path / "fused", scenario=two_stage_schedule(), fused_chunk=4
    )
    assert_bitwise_parity(host, fused, 4)
    assert host._scenario_rollouts == fused._scenario_rollouts == 4


def test_fused_chunk_of_one_with_scenarios_matches_host_loop(tmp_path):
    """The degenerate K=1 chunk still takes scenario xs with a leading
    (1,) axis (a length-1 scan is NOT the unscanned program) — the edge
    the rollouts>1 gate used to miss."""
    host = make_trainer(tmp_path / "host", scenario=two_stage_schedule())
    fused = make_trainer(
        tmp_path / "fused", scenario=two_stage_schedule(), fused_chunk=1
    )
    assert_bitwise_parity(host, fused, 1)


def test_fused_scan_bitwise_matches_host_loop_dp_mesh(tmp_path):
    from marl_distributedformation_tpu.parallel import make_shard_fn

    host = make_trainer(tmp_path / "host", shard_fn=make_shard_fn({"dp": 4}))
    fused = make_trainer(
        tmp_path / "fused", shard_fn=make_shard_fn({"dp": 4}), fused_chunk=2
    )
    assert_bitwise_parity(host, fused, 2)


# ---------------------------------------------------------------------------
# Compile-once (budget-1 RetraceGuard)
# ---------------------------------------------------------------------------


def test_fused_program_compiles_exactly_once_across_chunks_and_stages(
    tmp_path,
):
    """Three chunks crossing a scenario stage change + severity ramp =
    ONE compile of the fused program (guard_retraces=1 would raise on
    the retrace; the count is the receipt bench.py records)."""
    trainer = make_trainer(
        tmp_path, scenario=two_stage_schedule(), fused_chunk=2,
        guard_retraces=1,
    )
    for _ in range(3):
        trainer.run_chunk()
    assert trainer.retrace_guard.count == 1, (
        "the fused-scan program must compile exactly once per config"
    )


def test_run_iteration_refuses_fused_mode(tmp_path):
    trainer = make_trainer(tmp_path, fused_chunk=2)
    with pytest.raises(AssertionError, match="run_chunk"):
        trainer.run_iteration()
    host = make_trainer(tmp_path / "h")
    with pytest.raises(AssertionError, match="fused_chunk"):
        host.run_chunk()


# ---------------------------------------------------------------------------
# End-to-end: train() with double-buffered drain + async checkpoints
# ---------------------------------------------------------------------------


def test_fused_train_end_to_end_and_resume(tmp_path):
    """4 iterations in 2 fused chunks: per-iteration metrics records land
    in metrics.jsonl (same cadence as the host loop), the background
    writer produces discoverable checkpoints at chunk boundaries, and
    resume restores exactly — including re-entering the scenario
    schedule mid-ramp."""
    total = 4 * 3 * 4 * 4  # 4 iterations of M=4 x N=3 x n_steps=4

    def fused(**kw):
        return make_trainer(
            tmp_path,
            scenario=two_stage_schedule(),
            checkpoint=True,
            save_freq=8,
            total_timesteps=total,
            fused_chunk=2,
            guard_retraces=1,
            **kw,
        )

    trainer = fused()
    final = trainer.train()
    assert trainer.num_timesteps == total
    assert np.isfinite(final["loss"])
    assert trainer.retrace_guard.count == 1
    records = [
        json.loads(line)
        for line in (tmp_path / "logs" / "metrics.jsonl")
        .read_text()
        .splitlines()
    ]
    # Per-iteration records despite 2-iteration chunks, at host-loop
    # step stamps, each carrying its OWN schedule point's severity.
    assert [r["step"] for r in records] == [48, 96, 144, 192]
    sched = two_stage_schedule()
    np.testing.assert_allclose(
        [r["scenario_severity"] for r in records],
        [sched.severity_at(i) for i in range(4)],
    )
    path = latest_checkpoint(tmp_path / "logs")
    assert path is not None and "rl_model_192" in path.name

    resumed = fused(resume=True)
    assert resumed.num_timesteps == total
    assert resumed._scenario_rollouts == 4  # mid-schedule re-entry
    for a, b in zip(
        jax.tree_util.tree_leaves(trainer.train_state.params),
        jax.tree_util.tree_leaves(resumed.train_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_save_matches_sync_save_bytes(tmp_path):
    """save_async writes the same checkpoint the synchronous save would
    (device snapshot + writer thread change WHEN the bytes are produced,
    never WHAT they contain)."""
    a = make_trainer(tmp_path / "a", fused_chunk=2)
    b = make_trainer(tmp_path / "b", fused_chunk=2)
    a.run_chunk()
    b.run_chunk()
    sync_path = a.save()
    writer = AsyncCheckpointWriter()
    async_path = b.save_async(writer)
    writer.close()
    assert (
        pathlib.Path(sync_path).read_bytes()
        == pathlib.Path(async_path).read_bytes()
    )


# ---------------------------------------------------------------------------
# Async checkpoint pipeline: crash-safety + error surfacing
# ---------------------------------------------------------------------------


def test_async_writer_crash_mid_write_leaves_nothing_visible(
    tmp_path, monkeypatch
):
    """A persistent IO failure between the tmp write and the atomic
    rename (the worst possible moment) leaves no discoverable
    checkpoint — the dot-prefixed .tmp is invisible to
    latest_checkpoint (the _write_atomic invariant, now load-bearing
    from a background thread) — and, since the chaos hardening
    (docs/chaos.md), is retried then SKIPPED with audit instead of
    killing the training run: close() does not raise, the skip is
    counted, and the next write lands normally."""
    real_replace = pathlib.Path.replace

    def exploding_replace(self, target):
        if str(target).endswith(".msgpack"):
            raise OSError("disk gone mid-rename")
        return real_replace(self, target)

    monkeypatch.setattr(pathlib.Path, "replace", exploding_replace)
    writer = AsyncCheckpointWriter(io_retries=1, io_backoff_s=0.001)
    writer.submit(
        checkpoint_path(tmp_path, 5),
        {"params": np.zeros(3, np.float32), "num_timesteps": 5},
    )
    writer.close()  # degraded, not dead: no surfaced error
    assert writer.writes_skipped == 1
    assert latest_checkpoint(tmp_path) is None, (
        "a torn async write must never be discoverable"
    )
    monkeypatch.undo()
    # The writer recovers: a clean submit after the failure works.
    writer.submit(
        checkpoint_path(tmp_path, 6),
        {"params": np.zeros(3, np.float32), "num_timesteps": 6},
    )
    writer.close()
    assert latest_checkpoint(tmp_path).name == "rl_model_6_steps.msgpack"


def test_async_writer_error_surfaces_on_next_submit(tmp_path, monkeypatch):
    """PROGRAM errors (a serialization bug, a bad snapshot tree) still
    surface on the next submit — only IO weather degrades to
    skip-with-audit (tests/test_chaos.py pins that side)."""
    from marl_distributedformation_tpu.utils import checkpoint as ckpt_mod

    def boom(path, target):
        raise TypeError("unserializable leaf in snapshot tree")

    monkeypatch.setattr(ckpt_mod, "_write_atomic", boom)
    writer = AsyncCheckpointWriter()
    writer.submit(checkpoint_path(tmp_path, 1), {"x": np.zeros(2)})
    monkeypatch.undo()
    with pytest.raises(RuntimeError, match="async checkpoint"):
        writer.submit(checkpoint_path(tmp_path, 2), {"x": np.zeros(2)})


def test_async_writer_single_flight_is_ordered(tmp_path):
    """submit joins the previous write first: steps land on disk in
    submit order, so max-step discovery always sees a monotone set."""
    writer = AsyncCheckpointWriter()
    for step in (1, 2, 3):
        writer.submit(
            checkpoint_path(tmp_path, step),
            {"params": np.full(4, step, np.float32), "num_timesteps": step},
        )
    writer.close()
    assert latest_checkpoint(tmp_path).name == "rl_model_3_steps.msgpack"


# ---------------------------------------------------------------------------
# Fail-fasts: where fusion can't compose it must say so
# ---------------------------------------------------------------------------


def test_fused_chunk_fail_fasts(tmp_path):
    """The remaining non-composing combos stay loud. profile=true and
    the population sweeps COMPOSE now (tests/test_fused_sweep.py and
    test_profile_composes_with_fused_trainer below)."""
    from marl_distributedformation_tpu.train import HeteroTrainer

    with pytest.raises(SystemExit, match="exactly one"):
        make_trainer(tmp_path, fused_chunk=2, iters_per_dispatch=2)
    with pytest.raises(SystemExit, match="fused_chunk"):
        # The single-run curriculum trainer keeps its host-driven stage
        # loop (the POPULATION curriculum shell is the one that fuses).
        HeteroTrainer(
            env_params=EnvParams(num_agents=3),
            ppo=PPO,
            config=TrainConfig(
                num_formations=4, name="h", checkpoint=False,
                log_dir=str(tmp_path / "h"), fused_chunk=2,
            ),
        )


def test_profile_composes_with_fused_trainer(tmp_path):
    """profile=true + fused_chunk: chunk-granular trace captured into
    {log_dir}/profile/ with ZERO extra compiles (the combination used
    to fail-fast)."""
    trainer = make_trainer(
        tmp_path,
        fused_chunk=2,
        total_timesteps=4 * 3 * 4 * 4,  # 4 iterations = 2 chunks
        profile=True,
        profile_iterations=1,
        guard_retraces=1,
    )
    trainer.train()
    profile_dir = pathlib.Path(trainer.log_dir) / "profile"
    assert any(p.is_file() for p in profile_dir.rglob("*")), (
        f"no profiler trace captured under {profile_dir}"
    )
    assert trainer.retrace_guard.count == 1, (
        "tracing must not retrace the fused program"
    )
