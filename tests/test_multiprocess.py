"""REAL multi-process distributed training: two OS processes wired into one
JAX runtime over the gRPC coordination service, exercising the actual
multi-host code paths that every other test can only reach single-process:
``init_distributed`` env-var wiring, ``make_hybrid_mesh`` with
process-as-granule, ``reset_batch_sharded`` per-host shard construction,
globally-psummed training, coordinator-only checkpoint writes with the
durability barrier, and ``broadcast_restore`` resume.

The reference has no distributed anything (SURVEY.md §5); this pins the
replacement's cross-process contract on CPU (2 processes x 2 virtual
devices), the same wire-up a TPU pod uses.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from adam_budget import trajectory_rtol

REPO = Path(__file__).resolve().parent.parent

# Per-process SPMD programs may lower reductions in different orders
# (the ~3e-8 fp noise of the sharding parity tests), which Adam
# amplifies to O(lr) per update — so cross-process scalar gates use the
# explicit budget from tests/adam_budget.py instead of exact string
# equality of formatted floats. lr is the PPO default (1e-3) in every
# worker below; U is counted per worker at its gate.
_LR = 1e-3


def _parse_metric(outs, tag):
    """The '{tag}=<float>' values printed by both worker processes."""
    vals = [
        float(line.split(f"{tag}=")[1].split()[0])
        for out in outs
        for line in out.splitlines()
        if f"{tag}=" in line
    ]
    assert len(vals) == 2, f"expected {tag} from both processes: {vals}"
    return vals


def _skip_if_backend_cannot_multiprocess(outs):
    """Some jaxlib builds' CPU backend refuses multi-process collectives
    outright ('Multiprocess computations aren't implemented on the CPU
    backend') — then this test is unrunnable in the container, which is
    an environmental limitation, not a code failure."""
    if any(
        "Multiprocess computations aren't implemented" in out for out in outs
    ):
        pytest.skip(
            "this jaxlib's CPU backend lacks multi-process collectives; "
            "the cross-process contract needs real multi-host hardware"
        )

WORKER = """
import sys

# The worker runs from a tmp dir and the package may not be pip-installed
# (fresh machines): the repo root is substituted by the test harness.
sys.path.insert(0, "__REPO_ROOT__")

import jax

jax.config.update("jax_platforms", "cpu")

from marl_distributedformation_tpu.parallel import (
    init_distributed,
    make_hybrid_mesh,
    make_shard_fn,
)

assert init_distributed(), "env-var wiring must produce a multi-process runtime"
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.train import TrainConfig, Trainer

log_dir = sys.argv[1]
mesh = make_hybrid_mesh({"dp": -1})


def build(resume):
    return Trainer(
        EnvParams(num_agents=4, max_steps=8),
        ppo=PPOConfig(n_steps=2, batch_size=64, n_epochs=1),
        config=TrainConfig(
            num_formations=8,
            checkpoint=True,
            save_freq=1,
            name="mh",
            log_dir=log_dir,
            resume=resume,
        ),
        shard_fn=make_shard_fn(mesh=mesh),
    )


trainer = build(resume=False)
m = trainer.run_iteration()
loss = float(m["loss"])
assert loss == loss, "nan loss"
path = trainer.save()  # coordinator writes, both processes pass the barrier
if jax.process_index() == 0:
    assert path is not None, "coordinator must return the checkpoint path"
else:
    assert path is None, "non-coordinator must not claim a local file"
m2 = trainer.run_iteration()
print(f"TRAINED p{jax.process_index()} steps={trainer.num_timesteps}", flush=True)

resumed = build(resume=True)  # broadcast_restore: coordinator reads, all match
assert resumed.num_timesteps == 2 * 2 * 8 * 4 // 2, resumed.num_timesteps
m3 = resumed.run_iteration()
print(
    f"RESUMED p{jax.process_index()} steps={resumed.num_timesteps} "
    f"loss={float(m3['loss']):.4f}",
    flush=True,
)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training_and_broadcast_resume(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER.replace("__REPO_ROOT__", str(REPO)))
    log_dir = tmp_path / "logs"
    port = _free_port()

    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
        )
        env.pop("JAX_PLATFORMS", None)  # the worker pins cpu itself
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker), str(log_dir)],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    _skip_if_backend_cannot_multiprocess(outs)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"TRAINED p{pid}" in out, out
        assert f"RESUMED p{pid}" in out, out
    # The resume restored identical learner state everywhere: the
    # post-resume loss must agree across processes within the Adam
    # budget (the compared value sits behind 3 optimizer updates:
    # 2 pre-save iterations + 1 post-resume, 1 minibatch/epoch each).
    # atol floors the gate at the worker's %.4f print quantization.
    losses = _parse_metric(outs, "loss")
    np.testing.assert_allclose(
        losses[0], losses[1], rtol=trajectory_rtol(_LR, 3), atol=2e-4
    )
    # Exactly one checkpoint series on disk, written by the coordinator.
    files = sorted(log_dir.glob("rl_model_*_steps.msgpack"))
    assert files, "coordinator wrote no checkpoints"


SWEEP_WORKER = """
import sys

sys.path.insert(0, "__REPO_ROOT__")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from marl_distributedformation_tpu.parallel import (
    init_distributed,
    make_hybrid_mesh,
)

assert init_distributed(), "env-var wiring must produce a multi-process runtime"
assert jax.process_count() == 2 and len(jax.devices()) == 4

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.train import SweepTrainer, TrainConfig

log_dir = sys.argv[1]
mesh = make_hybrid_mesh({"dp": -1})
PPO = PPOConfig(n_steps=2, batch_size=12, n_epochs=1)
PER_ITER = 2 * 2 * 3  # n_steps * M * N agent-transitions per member


def build(resume, total):
    return SweepTrainer(
        EnvParams(num_agents=3, max_steps=8),
        ppo=PPO,
        config=TrainConfig(
            num_formations=2,
            checkpoint=True,
            save_freq=10**9,
            name="mhsweep",
            log_dir=log_dir,
            resume=resume,
            total_timesteps=total,
        ),
        num_seeds=4,
        mesh=mesh,
        learning_rates=[1e-3, 2e-3, 3e-3, 4e-3],
    )


sweep = build(resume=False, total=PER_ITER)
sweep.train()  # one iteration, then save() + summary on the coordinator
pre = sweep._to_host({"params": sweep.train_state.params})
print(f"TRAINED p{jax.process_index()} steps={sweep.num_timesteps}", flush=True)

resumed = build(resume=True, total=2 * PER_ITER)
assert resumed.num_timesteps == PER_ITER, resumed.num_timesteps
post = resumed._to_host({"params": resumed.train_state.params})
for a, b in zip(
    jax.tree_util.tree_leaves(pre), jax.tree_util.tree_leaves(post)
):
    assert (np.asarray(a) == np.asarray(b)).all(), "restore not bit-exact"
host_m = resumed._to_host(resumed.run_iteration())
print(
    f"RESUMED p{jax.process_index()} steps={resumed.num_timesteps} "
    f"reward0={float(host_m['reward'][0]):.6f}",
    flush=True,
)
"""


@pytest.mark.slow
def test_two_process_population_sweep(tmp_path):
    """Multi-host population sweep end-to-end: per-host member
    construction, SPMD training over the global mesh, coordinator-only
    member/population checkpoints, bit-exact broadcast resume."""
    worker = tmp_path / "sweep_worker.py"
    worker.write_text(SWEEP_WORKER.replace("__REPO_ROOT__", str(REPO)))
    log_dir = tmp_path / "logs"
    port = _free_port()

    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
        )
        env.pop("JAX_PLATFORMS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker), str(log_dir)],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    _skip_if_backend_cannot_multiprocess(outs)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"TRAINED p{pid}" in out, out
        assert f"RESUMED p{pid}" in out, out
    # The post-resume iteration is globally synchronized: member 0's
    # reward must agree across processes within the Adam budget (2
    # optimizer updates behind the compared value; member 0 trains at
    # the 1e-3 rate of the sweep's learning_rates).
    rewards = _parse_metric(outs, "reward0")
    np.testing.assert_allclose(
        rewards[0], rewards[1], rtol=trajectory_rtol(_LR, 2), atol=2e-6
    )
    # Coordinator wrote per-member checkpoints, the population state, and
    # the ranking summary.
    for i in range(4):
        assert list((log_dir / f"seed{i}").glob("rl_model_*_steps.msgpack"))
    assert list(log_dir.glob("sweep_state_*_steps.msgpack"))
    assert (log_dir / "sweep_summary.json").exists()


HETERO_WORKER = """
import sys

sys.path.insert(0, "__REPO_ROOT__")

import jax

jax.config.update("jax_platforms", "cpu")

from marl_distributedformation_tpu.parallel import (
    init_distributed,
    make_hybrid_mesh,
    make_shard_fn,
)

assert init_distributed(), "env-var wiring must produce a multi-process runtime"
assert jax.process_count() == 2 and len(jax.devices()) == 4

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.train import (
    Curriculum,
    CurriculumStage,
    HeteroTrainer,
    TrainConfig,
)

log_dir = sys.argv[1]
mesh = make_hybrid_mesh({"dp": -1})
CURRICULUM = Curriculum(
    stages=(
        CurriculumStage(rollouts=1, agent_counts=(3,)),
        CurriculumStage(rollouts=1, agent_counts=(3, 4), num_obstacles=1),
    )
)


def build(resume):
    return HeteroTrainer(
        curriculum=CURRICULUM,
        env_params=EnvParams(num_agents=3, max_steps=8),
        ppo=PPOConfig(n_steps=2, batch_size=32, n_epochs=1),
        config=TrainConfig(
            num_formations=8,
            checkpoint=True,
            save_freq=1,
            name="mh-hetero",
            log_dir=log_dir,
            resume=resume,
        ),
        shard_fn=make_shard_fn(mesh=mesh),
    )


trainer = build(resume=False)
trainer.train()  # both stages incl. the mixed-size + obstacle transition
assert trainer.completed_rollouts == 2, trainer.completed_rollouts
print(f"TRAINED p{jax.process_index()} steps={trainer.num_timesteps}", flush=True)

resumed = build(resume=True)  # broadcast restore incl. completed_rollouts
assert resumed.completed_rollouts == 2, resumed.completed_rollouts
assert resumed.num_timesteps == trainer.num_timesteps
# Continue past the recorded curriculum: re-enter the last stage and run
# one more globally synchronized iteration from the restored params.
resumed.start_stage(CURRICULUM.stages[-1])
loss = float(resumed.run_iteration()["loss"])
print(
    f"RESUMED p{jax.process_index()} steps={resumed.num_timesteps} "
    f"loss={loss:.6f}",
    flush=True,
)
"""


@pytest.mark.slow
def test_two_process_hetero_curriculum(tmp_path):
    """Multi-host heterogeneous curriculum end-to-end: per-host padded
    stage construction (hetero_reset_batch_sharded), a stage transition
    under SPMD, coordinator-only checkpoints, broadcast resume with the
    rollout cursor."""
    worker = tmp_path / "hetero_worker.py"
    worker.write_text(HETERO_WORKER.replace("__REPO_ROOT__", str(REPO)))
    log_dir = tmp_path / "logs"
    port = _free_port()

    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
        )
        env.pop("JAX_PLATFORMS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker), str(log_dir)],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    _skip_if_backend_cannot_multiprocess(outs)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"TRAINED p{pid}" in out, out
        assert f"RESUMED p{pid}" in out, out
    # Post-resume loss across processes, within the Adam budget (the
    # compared value sits behind 5 optimizer updates: 1 + 2 across the
    # two curriculum stages, then 2 more in the re-entered last stage).
    losses = _parse_metric(outs, "loss")
    np.testing.assert_allclose(
        losses[0], losses[1], rtol=trajectory_rtol(_LR, 5), atol=2e-6
    )
    assert list(log_dir.glob("rl_model_*_steps.msgpack"))
