"""Candidate-seed hetero-curriculum populations (train/hetero_sweep.py)."""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.train import (
    Curriculum,
    CurriculumStage,
    HeteroSweepTrainer,
    HeteroTrainer,
    TrainConfig,
)
from marl_distributedformation_tpu.parallel import make_mesh

PPO = PPOConfig(n_steps=4, batch_size=16, n_epochs=2)
CURR = Curriculum(
    stages=(
        CurriculumStage(rollouts=2, agent_counts=(3,)),
        CurriculumStage(rollouts=2, agent_counts=(3, 5), num_obstacles=1),
    )
)


def _cfg(tmp_path, **kw):
    base = dict(
        num_formations=4,
        seed=0,
        checkpoint=False,
        name="hsweep",
        log_dir=str(tmp_path / "logs"),
    )
    base.update(kw)
    return TrainConfig(**base)


def _leaves_allclose(a, b, rtol=1e-5, atol=1e-6):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol
        )


def _walk(trainer):
    """Drive the curriculum stage loop manually (both trainer shells
    expose start_stage/run_iteration)."""
    metrics = None
    for stage in trainer.curriculum.stages:
        trainer.start_stage(stage)
        for _ in range(stage.rollouts):
            metrics = trainer.run_iteration()
    return metrics


def test_member_matches_hetero_trainer(tmp_path):
    """Member i of a K=2 candidate population == HeteroTrainer(seed=i)
    through the FULL curriculum — same params, same metrics — so a
    population is exactly K reference single runs, fused."""
    sweep = HeteroSweepTrainer(
        curriculum=CURR,
        env_params=EnvParams(num_agents=3),
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=2,
    )
    singles = [
        HeteroTrainer(
            curriculum=CURR,
            env_params=EnvParams(num_agents=3),
            ppo=PPO,
            config=_cfg(tmp_path, seed=i),
        )
        for i in range(2)
    ]
    sweep_metrics = _walk(sweep)
    single_metrics = [_walk(t) for t in singles]
    for i, t in enumerate(singles):
        _leaves_allclose(
            jax.tree_util.tree_map(
                lambda x: x[i], sweep.train_state.params
            ),
            t.train_state.params,
        )
        np.testing.assert_allclose(
            float(sweep_metrics["reward"][i]),
            float(single_metrics[i]["reward"]),
            rtol=1e-5,
        )
        assert (
            int(sweep.num_timesteps_members[i]) == t.num_timesteps
        ), "active-transition accounting diverged from the single run"
    # Distinct candidates actually diverge.
    assert not np.allclose(
        np.asarray(sweep_metrics["reward"][0]),
        np.asarray(sweep_metrics["reward"][1]),
    )


@pytest.mark.slow
def test_member_axis_sharding_matches_unsharded(tmp_path):
    """mesh={dp: 4} shards the candidate axis with no effect beyond fp
    reduction-order noise, gated by the explicit Adam-amplification
    budget (tests/adam_budget.py: ~3e-8 lowering noise amplified to
    O(lr) per optimizer step — see test_sweep's twin gate)."""
    from adam_budget import adam_parity_atol, trajectory_rtol, updates_per_run

    plain = HeteroSweepTrainer(
        curriculum=CURR,
        env_params=EnvParams(num_agents=3),
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=4,
    )
    sharded = HeteroSweepTrainer(
        curriculum=CURR,
        env_params=EnvParams(num_agents=3),
        ppo=PPO,
        config=_cfg(tmp_path),
        num_seeds=4,
        mesh=make_mesh({"dp": 4}),
    )
    m_plain = _walk(plain)
    m_shard = _walk(sharded)
    # Per-member rows per iteration: n_steps * M * padded-N of the stage
    # (stage 2 pads its (3, 5) mix to N=5).
    updates = sum(
        updates_per_run(
            PPO,
            PPO.n_steps * 4 * max(stage.agent_counts),
            stage.rollouts,
        )
        for stage in CURR.stages
    )
    _leaves_allclose(
        plain.train_state.params,
        sharded.train_state.params,
        rtol=0,
        atol=adam_parity_atol(PPO.learning_rate, updates),
    )
    np.testing.assert_allclose(
        np.asarray(m_plain["reward"]),
        np.asarray(m_shard["reward"]),
        rtol=trajectory_rtol(PPO.learning_rate, updates),
    )


def test_checkpoints_and_summary_follow_sweep_contract(tmp_path):
    """train() lands per-member seed{i}/ checkpoints + sweep_summary.json
    — the artifact layout evaluate.py's member ranking and
    visualize_policy.py's best-member descent already consume."""
    config = _cfg(tmp_path, checkpoint=True, save_freq=4)
    sweep = HeteroSweepTrainer(
        curriculum=CURR,
        env_params=EnvParams(num_agents=3),
        ppo=PPO,
        config=config,
        num_seeds=2,
    )
    sweep.train()
    log_dir = Path(config.log_dir)
    for i in range(2):
        ckpts = list((log_dir / f"seed{i}").glob("rl_model_*_steps.msgpack"))
        assert ckpts, f"no member checkpoints under seed{i}/"
    summary = json.loads((log_dir / "sweep_summary.json").read_text())
    assert summary["seeds"] == [0, 1]
    assert summary["best_dir"] in ("seed0", "seed1")
    assert len(summary["final_reward"]) == 2


def test_resume_bit_exact_mid_stage(tmp_path):
    """An interrupted candidate block resumed from its sweep_state
    checkpoint ends bit-identical to an uninterrupted run — including a
    MID-stage interruption, where the partially-walked stage must NOT be
    resampled on resume."""
    env = EnvParams(num_agents=3)
    # 3 rollouts of stage 1 = the cap lands mid-stage-1 (stage 0 is 2).
    per_iter_max = PPO.n_steps * 4 * 3  # n_steps * M * N upper bound
    kw = dict(checkpoint=True, save_freq=10**9)

    full = HeteroSweepTrainer(
        curriculum=CURR, env_params=env, ppo=PPO, num_seeds=2,
        config=_cfg(tmp_path, name="full",
                    log_dir=str(tmp_path / "full"), **kw),
    )
    full.train()

    part = HeteroSweepTrainer(
        curriculum=CURR, env_params=env, ppo=PPO, num_seeds=2,
        config=_cfg(tmp_path, name="part",
                    log_dir=str(tmp_path / "part"),
                    total_timesteps=3 * per_iter_max, **kw),
    )
    part.train()  # budget cap stops mid-curriculum; final save() lands
    assert 0 < part.completed_rollouts < CURR.total_rollouts
    interrupted_at = part.completed_rollouts

    resumed = HeteroSweepTrainer(
        curriculum=CURR, env_params=env, ppo=PPO, num_seeds=2,
        config=_cfg(tmp_path, name="part",
                    log_dir=str(tmp_path / "part"), resume=True, **kw),
    )
    assert resumed.completed_rollouts == interrupted_at
    resumed.train()

    assert resumed.completed_rollouts == full.completed_rollouts
    for getter in (
        lambda t: t.train_state.params,
        lambda t: t.train_state.opt_state,
        lambda t: t.key,
        lambda t: t.env_state,
        lambda t: t.obs,
    ):
        la = jax.tree_util.tree_leaves(getter(resumed))
        lb = jax.tree_util.tree_leaves(getter(full))
        assert len(la) == len(lb)
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    np.testing.assert_array_equal(
        resumed.num_timesteps_members, full.num_timesteps_members
    )


def test_resume_rejects_identity_mismatch(tmp_path):
    env = EnvParams(num_agents=3)
    kw = dict(checkpoint=True, save_freq=10**9)
    t = HeteroSweepTrainer(
        curriculum=CURR, env_params=env, ppo=PPO, num_seeds=2,
        config=_cfg(tmp_path, name="a", log_dir=str(tmp_path / "a"), **kw),
    )
    t.train()
    with pytest.raises(SystemExit, match="num_seeds"):
        HeteroSweepTrainer(
            curriculum=CURR, env_params=env, ppo=PPO, num_seeds=1,
            config=_cfg(tmp_path, name="a", log_dir=str(tmp_path / "a"),
                        resume=True, **kw),
        )


def test_rejections(tmp_path):
    with pytest.raises(SystemExit, match="iters_per_dispatch"):
        HeteroSweepTrainer(
            curriculum=CURR,
            config=_cfg(tmp_path, iters_per_dispatch=2),
            num_seeds=2,
        )
    with pytest.raises(AssertionError, match="divisible"):
        HeteroSweepTrainer(
            curriculum=CURR,
            env_params=EnvParams(num_agents=3),
            ppo=PPO,
            config=_cfg(tmp_path),
            num_seeds=3,
            mesh=make_mesh({"dp": 4}),
        )


def test_cli_dispatch(tmp_path, monkeypatch):
    """train.py routes curriculum + num_seeds>1 to HeteroSweepTrainer and
    rejects the learning_rates combination."""
    import train as train_cli
    from marl_distributedformation_tpu.utils import load_config

    curr = (
        "curriculum=[{rollouts: 2, agent_counts: [3]}, "
        "{rollouts: 2, agent_counts: [3, 5]}]"
    )
    cfg = load_config(
        [
            "name=hsweep_cli", "num_seeds=2", "num_formation=4",
            "num_agents_per_formation=3", "n_steps=4", "batch_size=16",
            "n_epochs=2", "checkpoint=false", curr,
        ]
    )
    trainer = train_cli.build_trainer(cfg)
    assert isinstance(trainer, HeteroSweepTrainer)
    assert trainer.num_seeds == 2
    cfg_bad = load_config(
        [
            "name=x", "num_seeds=2", "learning_rates=[1e-3,1e-4]", curr,
        ]
    )
    with pytest.raises(SystemExit, match="learning_rates"):
        train_cli.build_trainer(cfg_bad)
